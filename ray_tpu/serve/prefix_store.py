"""Cluster prefix-cache economy: tiered KV store with cross-replica
prefix sharing.

The per-engine radix prefix cache (serve/kv_blocks.py) caps the
cluster's aggregate cache at ONE engine's HBM pool: a replica that
misses re-prefills even when a sibling — or the object plane — already
holds the exact KV pages.  This module composes the two proofs the
earlier rounds established (KV pages travel the object plane
token-identically; caches must be policy-versioned) into a three-tier
store:

  - **Tier 1** — the engine's HBM radix tree, unchanged.
  - **Tier 2** — cold subtrees demoted leaf-first into SEALED arena
    objects: one object per demoted leaf, holding the KV of the whole
    path root..leaf in the kv_export page layout
    ([2, L, depth, kvh, page, hd]), indexed by the chained blake2b
    block hashes the router already gossips (kv_router.chain_hash — a
    hash h_i commits to the entire prefix through block i, so index
    membership alone proves which slice of the object serves a prompt).
  - **Tier 3** — arena disk spill, for free: sealed objects under
    memory pressure spill like any other object and page back in on
    pull.

Two halves, both dependency-light so the layering invariant holds
(core primitives + public facades + serve siblings only):

  - **StoreDirectory** (controller-side): hash → entry index over the
    published objects.  Every entry is tagged with the publishing
    engine's `seed` and `weight_version`, so an RLHF weight swap
    INVALIDATES instead of corrupting — a version-mismatched entry is
    never returned by lookup.  The directory holds a borrowed ObjectRef
    per entry; dropping an entry releases it, and the owner's free path
    scrubs every node's replica (the add_location invariant — pulls go
    through the normal `ray_tpu.get`, never around the announcement).
  - **PrefixStoreClient** (replica-side): owns the published objects'
    primary refs, publishes demoted subtrees (the engine's demotion
    callback), and runs the miss path: on a shallow local radix match,
    look up the deepest cluster-resident prefix and — gated by the cost
    model below — pull + graft it into the local pool instead of
    re-prefilling.

Cost model: prefill FLOPs avoided vs migration cost.  The seed
constant is the measured ~4.7 ms/migration figure from the PD-disagg
rounds (RAY_TPU_PREFIX_STORE_MIGRATE_MS); the per-token prefill cost
and pull bandwidth are env-tunable too, and a deployment can override
all three through its `prefix_store` config dict.

Kill switch: RAY_TPU_PREFIX_STORE=0 (read per request — same-run A/B),
plus the per-request payload key {"prefix_store": false}.  Failpoint
sites: serve.prefix_demote (publish leg), serve.prefix_fetch (pull
leg), serve.prefix_graft (engine-loop graft, armed in serve/llm.py).
Flight-recorder spans ride the same three legs.
"""
from __future__ import annotations

import logging
import os
import threading
import time

from ray_tpu import tracing
from ray_tpu.serve.kv_router import (matched_depth,  # noqa: F401
                                     prefix_store_on)

logger = logging.getLogger(__name__)

# Named actor the client resolves lazily (literal, NOT imported from
# serve/controller.py: the controller imports this module for its
# directory, and the reverse import would cycle).
_CONTROLLER_NAME = "SERVE_CONTROLLER"

# Cost-model seed constants (env-tunable; per-deployment overrides ride
# the `prefix_store` config dict).  MIGRATE_MS is the measured fixed
# cost of one KV migration through the object plane (~4.7 ms on the
# bench box: put + lookup RT + pull dispatch); PREFILL_US_PER_TOKEN is
# the prefill compute a grafted token avoids; BW_GBPS prices the pull's
# byte volume (same-host direct-shm pulls run far above this — the
# default is deliberately the conservative cross-node figure).
_DEFAULT_MIGRATE_MS = 4.7
_DEFAULT_PREFILL_US_PER_TOKEN = 40.0
_DEFAULT_BW_GBPS = 2.0


# prefix_store_on is DEFINED in kv_router with its sibling
# cluster-serving switches (one copy — the legs must never drift) and
# re-exported here for the natural import site.

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _object_plane_ready() -> bool:
    """True when this process can put/get arena objects: an
    initialized driver OR a connected worker (replicas are workers —
    ray_tpu.is_initialized() is a DRIVER-side flag and stays False in
    them)."""
    import ray_tpu

    if ray_tpu.is_initialized():
        return True
    try:
        from ray_tpu.runtime_context import get_runtime_context

        get_runtime_context()
        return True
    except Exception:  # noqa: BLE001 - no worker in this process
        return False


def migration_worth_it(tokens_saved: int, nbytes: int,
                       config: dict | None = None) -> bool:
    """Graft only when the prefill time avoided beats the migration
    cost (fixed per-migration overhead + the object's bytes at pull
    bandwidth).  Config keys override the env knobs override the seed
    constants."""
    cfg = config or {}
    migrate_ms = cfg.get("migrate_ms", _env_float(
        "RAY_TPU_PREFIX_STORE_MIGRATE_MS", _DEFAULT_MIGRATE_MS))
    us_per_tok = cfg.get("prefill_us_per_token", _env_float(
        "RAY_TPU_PREFIX_STORE_PREFILL_US_PER_TOKEN",
        _DEFAULT_PREFILL_US_PER_TOKEN))
    bw_gbps = cfg.get("bw_gbps", _env_float(
        "RAY_TPU_PREFIX_STORE_BW_GBPS", _DEFAULT_BW_GBPS))
    benefit_ms = tokens_saved * us_per_tok / 1000.0
    cost_ms = migrate_ms + nbytes / max(bw_gbps, 1e-6) / 1e6
    return benefit_ms > cost_ms


class StoreDirectory:
    """Controller-side index of the cluster's demoted prefix objects.

    One instance lives on the ServeController (thread-safe: the
    controller is a threaded actor); tests may also instantiate one
    directly and hand it to a PrefixStoreClient, which then calls it
    in-process instead of over RPC.

    Entries are keyed by the demoted LEAF's chained hash; the index
    maps EVERY hash along the entry's chain to (leaf, depth), so a
    prompt matching only part of a demoted path still finds the entry
    and grafts the matching slice.  Byte budget
    (RAY_TPU_PREFIX_STORE_MAX_BYTES) evicts oldest-published first —
    dropping an entry releases the directory's borrowed ref; the
    publisher's own ref (and ultimately the owner free path, which
    scrubs every announced replica location) does the rest.
    """

    def __init__(self, max_bytes: int | None = None):
        self._lock = threading.Lock()
        self._max_bytes = max_bytes if max_bytes is not None else \
            _env_int("RAY_TPU_PREFIX_STORE_MAX_BYTES", 1 << 30)
        # app -> {"entries": {leaf_hash: entry}, "index": {hash: (leaf, depth)}}
        self._apps: dict[str, dict] = {}
        self._bytes = 0
        self.published = 0
        self.evicted = 0
        self.forgotten = 0
        self.lookups = 0
        self.lookup_hits = 0

    # ------------------------------------------------------------ write
    def publish(self, app: str, meta: dict, ref) -> dict:
        """Register one demoted subtree.  `meta` carries the chain
        hashes (root..leaf), page size, engine seed, weight version,
        byte size, and the publishing replica's id; `ref` is the sealed
        arena object (kv_export layout, depth == len(hashes)).

        Returns {"ok": bool, "live": [leaf hashes]} — `ok` is False
        when the entry did NOT survive registration (e.g. it was
        immediately evicted by the byte cap): the publisher must then
        KEEP its tier-1 copy.  `live` lists every entry the directory
        still holds for this replica, so the publisher can drop the
        primary refs of entries the directory evicted/forgot since —
        without this reconciliation the byte cap would bound only the
        index while the arena bytes leaked until replica shutdown."""
        # The ref arrives nested (one-element list) when it crosses the
        # controller RPC: a top-level ObjectRef arg would be resolved
        # to the whole KV array before execution, making the directory
        # hold tier-2 bytes host-side instead of a borrowed ref.
        if isinstance(ref, list):
            ref = ref[0]
        hashes = [int(h) for h in meta["hashes"]]
        if not hashes:
            return {"ok": False, "live": []}
        leaf = hashes[-1]
        entry = {
            "ref": ref,
            "hashes": hashes,
            "page": int(meta["page"]),
            "seed": meta.get("seed"),
            "weight_version": int(meta.get("weight_version", 0)),
            "nbytes": int(meta.get("nbytes", 0)),
            "replica": meta.get("replica"),
            "deployment": meta.get("deployment"),
            "t": time.monotonic(),
        }
        replica = meta.get("replica")
        with self._lock:
            if entry["nbytes"] > self._max_bytes:
                # An entry that can NEVER fit must not evict healthy
                # siblings on its way to being evicted itself.
                a = self._apps.get(app)
                live = [h for h, e in (a["entries"].items() if a
                                       else ()) if e["replica"] == replica]
                return {"ok": False, "live": live}
            a = self._apps.setdefault(app, {"entries": {}, "index": {}})
            old = a["entries"].pop(leaf, None)
            if old is not None:
                self._bytes -= old["nbytes"]
            a["entries"][leaf] = entry
            self._bytes += entry["nbytes"]
            self._reindex_locked(a)
            self.published += 1
            self._evict_over_cap_locked()
            # The cap may have evicted the very entry being published
            # (oldest, or larger than the whole budget): report that —
            # a True here would make the engine drop the LAST copy.
            a = self._apps.get(app)
            ok = a is not None and a["entries"].get(leaf) is entry
            live = [h for h, e in (a["entries"].items() if a else ())
                    if e["replica"] == replica]
        return {"ok": ok, "live": live}

    def _reindex_locked(self, a: dict) -> None:
        idx: dict[int, tuple[int, int]] = {}
        for leaf, e in a["entries"].items():
            for i, h in enumerate(e["hashes"]):
                idx.setdefault(h, (leaf, i + 1))
        a["index"] = idx

    def _evict_over_cap_locked(self) -> None:
        while self._bytes > self._max_bytes:
            oldest = None
            for app, a in self._apps.items():
                for leaf, e in a["entries"].items():
                    if oldest is None or e["t"] < oldest[2]["t"]:
                        oldest = (app, leaf, e)
            if oldest is None:
                return
            app, leaf, e = oldest
            a = self._apps[app]
            del a["entries"][leaf]
            self._bytes -= e["nbytes"]
            self._reindex_locked(a)
            self.evicted += 1

    def forget(self, app: str, replica: str | None = None,
               below_version: int | None = None,
               hashes: list | None = None) -> int:
        """Drop entries by replica / weight-version bound / explicit
        leaf hashes.  Returns the number dropped."""
        drop_hashes = {int(h) for h in hashes} if hashes else None
        n = 0
        with self._lock:
            a = self._apps.get(app)
            if a is None:
                return 0
            for leaf, e in list(a["entries"].items()):
                if replica is not None and e["replica"] != replica:
                    continue
                if below_version is not None \
                        and e["weight_version"] >= below_version:
                    continue
                if drop_hashes is not None and leaf not in drop_hashes:
                    continue
                del a["entries"][leaf]
                self._bytes -= e["nbytes"]
                n += 1
            if n:
                self._reindex_locked(a)
                self.forgotten += n
            if not a["entries"]:
                self._apps.pop(app, None)
        return n

    def drop_app(self, app: str) -> int:
        with self._lock:
            a = self._apps.pop(app, None)
            if a is None:
                return 0
            n = len(a["entries"])
            self._bytes -= sum(e["nbytes"] for e in a["entries"].values())
            self.forgotten += n
        return n

    def drop_replica(self, replica: str) -> int:
        """Scrub a dead replica's entries everywhere (its objects die
        with the owning process — lookups against them would only
        fail)."""
        n = 0
        for app in list(self._apps):
            n += self.forget(app, replica=replica)
        return n

    def clear(self) -> int:
        with self._lock:
            n = sum(len(a["entries"]) for a in self._apps.values())
            self._apps.clear()
            self._bytes = 0
            self.forgotten += n
        return n

    # ------------------------------------------------------------- read
    def lookup(self, app: str, hashes: list, page: int, seed,
               weight_version: int | None = None,
               min_depth: int = 0) -> dict | None:
        """Deepest stored prefix of a prompt's hash chain, filtered by
        page/seed/weight_version (a mismatched entry is skipped, never
        returned — the RLHF-swap safety contract).  `min_depth` is the
        caller's local radix depth: only a STRICTLY deeper stored
        prefix is worth a migration."""
        with self._lock:
            self.lookups += 1
            a = self._apps.get(app)
            if a is None:
                return None
            for i in range(len(hashes) - 1, min_depth - 1, -1):
                hit = a["index"].get(int(hashes[i]))
                if hit is None:
                    continue
                leaf, _d = hit
                e = a["entries"].get(leaf)
                if e is None:
                    continue
                if e["page"] != page:
                    continue
                if seed is not None and e["seed"] is not None \
                        and e["seed"] != seed:
                    continue
                if weight_version is not None \
                        and e["weight_version"] != weight_version:
                    continue
                self.lookup_hits += 1
                return {"ref": e["ref"], "depth": i + 1,
                        "entry_depth": len(e["hashes"]),
                        "nbytes": e["nbytes"], "hash": leaf,
                        "weight_version": e["weight_version"],
                        "replica": e["replica"]}
        return None

    def summary(self, app: str) -> dict:
        """The app's cluster-resident prefix hashes, grouped by page
        size — the router-side view (handle.py polls this next to the
        replica summaries so scoring can see prefixes no live radix
        tree holds)."""
        with self._lock:
            a = self._apps.get(app)
            pages: dict[int, list[int]] = {}
            n = 0
            if a is not None:
                n = len(a["entries"])
                for h, (leaf, _d) in a["index"].items():
                    e = a["entries"].get(leaf)
                    if e is not None:
                        pages.setdefault(e["page"], []).append(h)
            return {"pages": pages, "entries": n}

    def bytes_by_deployment(self) -> dict[tuple[str, str], int]:
        """(app, deployment) -> tier-2 bytes — the per-deployment gauge
        the serve controller publishes (memory-ledger observability)."""
        out: dict[tuple[str, str], int] = {}
        with self._lock:
            for app, a in self._apps.items():
                for e in a["entries"].values():
                    key = (app, e.get("deployment") or "?")
                    out[key] = out.get(key, 0) + e["nbytes"]
        return out

    def replicas(self) -> set[str]:
        """Every replica id with at least one live entry (the serve
        controller's tier-2 orphan check compares these against its
        live replica set)."""
        with self._lock:
            return {e["replica"] for a in self._apps.values()
                    for e in a["entries"].values()
                    if e.get("replica")}

    def stats(self) -> dict:
        with self._lock:
            return {
                "apps": len(self._apps),
                "entries": sum(len(a["entries"])
                               for a in self._apps.values()),
                "bytes": self._bytes,
                "published": self.published,
                "evicted": self.evicted,
                "forgotten": self.forgotten,
                "lookups": self.lookups,
                "lookup_hits": self.lookup_hits,
            }


class PrefixStoreClient:
    """Replica-side half: publishes demoted subtrees and runs the
    miss-path fetch/graft.  Owns the primary ObjectRef of every object
    this replica published — `close()` (replica shutdown / app delete)
    drops them all and tells the directory to forget, so tier-2 never
    outlives its app (the kv_check leak contract)."""

    def __init__(self, *, app: str, deployment: str, replica_id: str,
                 seed, page: int, config: dict | None = None,
                 directory: StoreDirectory | None = None):
        self._app = app or "default"
        self._deployment = deployment
        self._replica_id = replica_id
        self._seed = seed
        self._page = page
        self._cfg = dict(config or {})
        self._directory = directory
        self._ctrl = None
        self._ctrl_retry_at = 0.0
        self._lock = threading.Lock()
        # leaf hash -> (ref, weight_version, nbytes): the primary refs.
        self._objects: dict[int, tuple] = {}
        # Graft coalescing: entry hash -> Event for the in-flight pull;
        # concurrent requests for one hot prefix must not pull the
        # object once each — followers wait and then prefix-hit the
        # leader's grafted blocks in tier 1.
        self._graft_inflight: dict[int, threading.Event] = {}
        self._closed = False
        self.published = 0
        self.publish_bytes = 0
        self.fetches = 0
        self.fetch_bytes = 0
        self.grafts = 0
        self.graft_tokens = 0
        self.fallbacks = 0
        self.stale_rejected = 0
        self.lookup_misses = 0
        self.cost_skipped = 0

    # -------------------------------------------------------- transport
    def _controller(self):
        if self._directory is not None:
            return None
        if not _object_plane_ready():
            return None
        import ray_tpu

        with self._lock:
            if self._ctrl is not None:
                return self._ctrl
            if time.monotonic() < self._ctrl_retry_at:
                return None
        try:
            ctrl = ray_tpu.get_actor(_CONTROLLER_NAME)
        except Exception:  # noqa: BLE001 - serve not running
            with self._lock:
                self._ctrl_retry_at = time.monotonic() + 5.0
            return None
        with self._lock:
            self._ctrl = ctrl
        return ctrl

    def _call(self, verb: str, *args, timeout: float = 10.0,
              default=None, **kwargs):
        """Directory call: in-process when a directory was injected
        (tests), otherwise through the controller's prefix_store_*
        RPC verbs."""
        if self._directory is not None:
            return getattr(self._directory, verb)(*args, **kwargs)
        ctrl = self._controller()
        if ctrl is None:
            return default
        import ray_tpu

        try:
            ref = getattr(ctrl, "prefix_store_" + verb).remote(
                *args, **kwargs)
            return ray_tpu.get(ref, timeout=timeout)
        except Exception:  # noqa: BLE001 - controller restarting
            with self._lock:
                self._ctrl = None
                self._ctrl_retry_at = time.monotonic() + 5.0
            return default

    # ---------------------------------------------------------- publish
    def publish(self, entry: dict) -> bool:
        """Demotion callback (runs on the engine's export thread):
        seal the subtree's host KV into an arena object and register it
        with the directory.  Returns True when tier 2 holds the entry —
        the engine's cue that evicting the tier-1 leaf loses nothing.
        entry: {tokens, kv, hashes, depth, page, weight_version}.
        (The serve.prefix_demote failpoint fires on the ENGINE side of
        this callback — llm.py _demote_one — so the fault window covers
        any publisher.)"""
        if self._closed or not prefix_store_on():
            return False
        h = int(entry["hashes"][-1])
        version = int(entry.get("weight_version", 0))
        kv = entry["kv"]
        t0 = time.time()
        with self._lock:
            cur = self._objects.get(h)
        if cur is not None and cur[1] == version:
            # Already sealed under this version: reuse the object, but
            # ALWAYS re-register with the directory — its copy of the
            # entry may be gone (byte-cap eviction, a failed-fetch
            # scrub, a restarted controller), and returning True on the
            # local cache alone would let the engine drop the LAST
            # remaining copy of the prefix.
            ref, nbytes = cur[0], cur[2]
        elif _object_plane_ready():
            import ray_tpu
            from ray_tpu import memledger

            with memledger.tag(
                    "prefix_tier2",
                    label=f"serve/prefix_store.py tier2 "
                          f"{self._deployment}"):
                ref = ray_tpu.put(kv)
            nbytes = int(kv.nbytes)
        elif self._directory is not None:
            # In-process directory with no object plane (unit tests):
            # the host array itself is the payload.
            ref, nbytes = kv, int(kv.nbytes)
        else:
            return False
        meta = {"hashes": [int(x) for x in entry["hashes"]],
                "page": int(entry["page"]), "seed": self._seed,
                "weight_version": version, "nbytes": nbytes,
                "replica": self._replica_id,
                "deployment": self._deployment}
        # Nest the ref so it survives the RPC as a ref (top-level
        # ObjectRef args resolve to values before execution — the
        # directory would end up holding the KV bytes themselves).
        reply = self._call("publish", self._app, meta, [ref],
                           default=None)
        ok = bool(reply and reply.get("ok"))
        if tracing.ENABLED:
            tracing.emit("serve.prefix_demote", t0, attrs={
                "bytes": nbytes, "depth": int(entry["depth"]),
                "weight_version": version, "ok": ok})
        if not ok:
            del ref
            with self._lock:
                self._objects.pop(h, None)
            return False
        with self._lock:
            if self._closed:
                # Shutdown raced the publish: withdraw immediately so
                # the object can't outlive the app.
                self._objects.pop(h, None)
                ok = False
            else:
                if cur is None:
                    self.published += 1
                    self.publish_bytes += nbytes
                self._objects[h] = (ref, version, nbytes)
                # Reconcile against the directory's view: entries it
                # evicted/forgot since our last publish are unreachable
                # — holding their primary refs would leak arena bytes
                # past the configured cap until replica shutdown.
                live = {int(x) for x in reply.get("live", ())}
                live.add(h)
                for stale in [k for k in self._objects
                              if k not in live]:
                    del self._objects[stale]
        if not ok:
            self._call("forget", self._app, hashes=[h], timeout=5.0)
        return bool(ok)

    # ------------------------------------------------------------ fetch
    def maybe_graft(self, engine, prompt: list, *,
                    salt: int = 0) -> dict:
        """The miss path (blocking; callers run it off the event loop):
        compare the local radix match against the cluster directory and
        — when the cost model approves — pull the stored prefix and
        graft it into the engine's pool.  Every failure degrades to a
        local prefill, never fails the request.  `salt` is the
        request's adapter KV identity (serve/lora.adapter_salt): the
        chain hashes — and with them the directory lookup and the
        graft's radix commit — are salt-distinct, so a stored prefix
        only ever serves the (adapter, version) that computed it."""
        from ray_tpu.serve import kv_router

        out = {"grafted": 0}
        page = engine.page
        hashes = kv_router.prompt_hashes(prompt, page, salt)
        if not hashes:
            return out
        local_summary = engine._mgr.prefix_summary()
        local = matched_depth(hashes, frozenset(local_summary["hashes"]))
        max_gain = (len(hashes) - local) * page
        min_tokens = int(self._cfg.get("min_tokens", page))
        # Pre-gate on the BEST-CASE gain: when even a full-depth hit
        # couldn't beat the migration cost, skip the directory RT
        # entirely (the lookup is a controller round trip).
        if max_gain < min_tokens \
                or not migration_worth_it(max_gain, 0, self._cfg):
            return out
        entry = self._call("lookup", self._app, [int(h) for h in hashes],
                           page, self._seed, engine.weight_version,
                           min_depth=local, default=None)
        if not entry:
            self.lookup_misses += 1
            return out
        depth = int(entry["depth"])
        tokens_saved = (depth - local) * page
        if tokens_saved < min_tokens or not migration_worth_it(
                tokens_saved, int(entry.get("nbytes", 0)), self._cfg):
            self.cost_skipped += 1
            return out
        h = int(entry["hash"])
        with self._lock:
            leader = self._graft_inflight.get(h)
            if leader is None:
                self._graft_inflight[h] = threading.Event()
            # else: follower — wait below, outside the lock.
        if leader is not None:
            leader.wait(timeout=60.0)
            return {"grafted": 0, "reason": "coalesced"}
        from ray_tpu import failpoints

        pulled = False
        try:
            try:
                if failpoints.ACTIVE:
                    failpoints.fire("serve.prefix_fetch")
                import numpy as np

                from ray_tpu.object_ref import ObjectRef

                with tracing.span("serve.prefix_fetch", attrs={
                        "depth": depth, "local_depth": local,
                        "bytes": int(entry.get("nbytes", 0)),
                        "replica": entry.get("replica")}):
                    payload = entry["ref"]
                    if isinstance(payload, ObjectRef):
                        import ray_tpu

                        payload = ray_tpu.get(payload, timeout=30.0)
                    blob = np.asarray(payload)
                pulled = True
                self.fetches += 1
                self.fetch_bytes += int(blob.nbytes)
                kv = blob[:, :, :depth]
                with tracing.span("serve.prefix_graft", attrs={
                        "tokens": depth * page,
                        "saved": tokens_saved}):
                    res = engine.kv_graft(
                        list(prompt[:depth * page]), kv,
                        kv_len=depth * page,
                        weight_version=entry.get("weight_version"),
                        salt=salt,
                    ).result(timeout=60.0)
                del blob, kv
            except BaseException:  # noqa: BLE001 - degrade, never fail
                self.fallbacks += 1
                if not pulled:
                    # A FAILED PULL is the dead-publisher signature —
                    # scrub the doomed entry (the publisher re-registers
                    # on its next demotion if it is in fact alive).
                    # Post-pull failures (a busy engine timing out the
                    # graft) say nothing about the entry: keep it.
                    self._call("forget", self._app,
                               hashes=[entry["hash"]], timeout=5.0)
                return out
        finally:
            with self._lock:
                ev = self._graft_inflight.pop(h, None)
            if ev is not None:
                ev.set()
        if res.get("grafted"):
            self.grafts += 1
            self.graft_tokens += tokens_saved
            return res
        if res.get("reason") == "stale_version":
            self.stale_rejected += 1
        else:
            self.fallbacks += 1
        return res

    # -------------------------------------------------------- lifecycle
    def invalidate(self, current_version: int) -> int:
        """Live weight swap: every entry published under an OLDER
        weight version is stale — drop the primary refs and tell the
        directory to forget (lookup's version filter already refuses
        them; this reclaims the arena bytes too)."""
        dropped = 0
        with self._lock:
            for h, (ref, v, nbytes) in list(self._objects.items()):
                if v < current_version:
                    del self._objects[h]
                    dropped += 1
        if dropped:
            self._call("forget", self._app, replica=self._replica_id,
                       below_version=current_version, timeout=5.0)
        return dropped

    def object_count(self) -> int:
        with self._lock:
            return len(self._objects)

    def close(self) -> None:
        """Replica shutdown / app delete: drop every published object's
        primary ref and withdraw from the directory — demoted subtrees
        must not outlive their app."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            had = bool(self._objects)
            self._objects.clear()
        if had:
            self._call("forget", self._app, replica=self._replica_id,
                       timeout=5.0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "objects": len(self._objects),
                "object_bytes": sum(o[2]
                                    for o in self._objects.values()),
                "published": self.published,
                "publish_bytes": self.publish_bytes,
                "fetches": self.fetches,
                "fetch_bytes": self.fetch_bytes,
                "grafts": self.grafts,
                "graft_tokens": self.graft_tokens,
                "fallbacks": self.fallbacks,
                "stale_rejected": self.stale_rejected,
                "lookup_misses": self.lookup_misses,
                "cost_skipped": self.cost_skipped,
            }
