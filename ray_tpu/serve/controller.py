"""ServeController: the reconciling control plane for Serve.

Analog of ray: python/ray/serve/_private/controller.py (ServeController,
run_control_loop:372) + deployment_state.py (DeploymentState reconciler) +
autoscaling_state.py (autoscaling policy) + deployment_scheduler.py.

A *threaded* actor (not asyncio): the control loop and RPC methods run on
the actor's thread pool so they may freely make blocking framework calls
(create actor / get / kill) — the same reason the reference runs its
reconciler off the replica event loops.  Replica membership is versioned;
handles poll `get_deployment_info` (the long-poll analog of ray:
_private/long_poll.py LongPollHost).

Concurrency discipline: the controller lock only guards in-memory state —
no RPC is ever made while holding it.  Replica starts/health checks are
asynchronous (pending ObjectRefs polled each reconcile tick), so one slow
replica init never stalls reconciliation of other deployments (ray:
deployment_state starts replicas async and polls readiness).
"""
from __future__ import annotations

import logging
import threading
import time
import traceback
import uuid
from typing import Any

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
RECONCILE_PERIOD_S = 0.2
REPLICA_INIT_TIMEOUT_S = 120.0


class _DeploymentState:
    """Target spec + live replicas for one deployment (ray:
    deployment_state.py DeploymentState)."""

    def __init__(self, app: str, name: str, cls, init_args, init_kwargs,
                 config, version: str):
        self.app = app
        self.name = name
        self.cls = cls
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.version = version
        self.target_replicas = config.num_replicas
        # replica actor_id -> {"handle", "state", "init_ref", "init_deadline",
        #                      "health_ref", "health_deadline", "last_health"}
        self.replicas: dict[str, dict] = {}
        # Old-version replicas still serving during a rolling code update;
        # advertised only until the new version is up (ray: gradual rollout).
        self.draining: dict[str, dict] = {}
        self.membership_version = 0
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.deleting = False
        self.superseded = False   # replaced by a newer _DeploymentState
        # autoscale probe in flight: list of (rec, ref) + deadline
        self.probe: tuple[list, float] | None = None


class ServeController:
    """Named detached actor; one per cluster (ray: controller.py:86)."""

    def __init__(self):
        self._lock = threading.RLock()
        # app -> {"route_prefix", "ingress", "deployments": {name: state}}
        self._apps: dict[str, dict] = {}
        self._http_host = "127.0.0.1"
        self._http_port = 0
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._run_control_loop, daemon=True, name="serve-ctrl")
        self._thread.start()

    # ------------------------------------------------------------ public RPC
    def deploy_app(self, app_name: str, route_prefix: str, ingress: str,
                   deployments: list[dict]) -> None:
        """Declarative (re)deploy of a whole app (ray: serve.run →
        controller.deploy_apps).  Never blocks on replica RPCs: code
        changes hand old replicas to the new state's drain list; config
        changes are applied by the reconcile loop."""
        reconfigures: list[tuple[Any, Any]] = []
        with self._lock:
            app = self._apps.setdefault(
                app_name, {"route_prefix": route_prefix, "ingress": ingress,
                           "deployments": {}})
            app["route_prefix"] = route_prefix
            app["ingress"] = ingress
            new_names = {d["name"] for d in deployments}
            for name, st in list(app["deployments"].items()):
                if name not in new_names:
                    st.deleting = True
                    st.target_replicas = 0
            for d in deployments:
                cur = app["deployments"].get(d["name"])
                if cur is not None and cur.version == d["version"] \
                        and not cur.deleting:
                    # Config-only change: rescale/reconfigure in place
                    # (ray: deployment_state config-change classification).
                    old_user_config = cur.config.user_config
                    cur.config = d["config"]
                    if cur.config.autoscaling_config is None:
                        cur.target_replicas = d["config"].num_replicas
                    if d["config"].user_config is not None and \
                            d["config"].user_config != old_user_config:
                        reconfigures.append((cur, d["config"].user_config))
                    continue
                new_st = _DeploymentState(
                    app_name, d["name"], d["cls"], d["init_args"],
                    d["init_kwargs"], d["config"], d["version"])
                if cur is not None:
                    cur.superseded = True
                    # Old replicas keep serving until the new version is up.
                    new_st.draining.update(cur.replicas)
                    new_st.draining.update(cur.draining)
                app["deployments"][d["name"]] = new_st
        for st, user_config in reconfigures:
            self._reconfigure_in_place(st, user_config)

    def _reconfigure_in_place(self, st: _DeploymentState, user_config) -> None:
        import ray_tpu

        with self._lock:
            handles = [rec["handle"] for rec in st.replicas.values()
                       if rec["state"] == "RUNNING"]
        refs = [h.reconfigure.remote(user_config) for h in handles]
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=30.0)
            except Exception:  # noqa: BLE001
                logger.warning("reconfigure failed:\n%s",
                               traceback.format_exc())

    def delete_app(self, app_name: str) -> None:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return
            for st in app["deployments"].values():
                st.deleting = True
                st.target_replicas = 0

    def get_deployment_info(self, app_name: str, deployment: str) -> dict:
        with self._lock:
            st = self._state(app_name, deployment)
            if st is None:
                return {"version": -1, "replicas": [], "max_ongoing": 0}
            running = [rid for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"]
            if not running:
                # During a rolling update the old version keeps serving.
                running = [rid for rid, rec in st.draining.items()
                           if rec["state"] == "RUNNING"]
            return {
                "version": st.membership_version,
                "replicas": running,
                "max_ongoing": st.config.max_ongoing_requests,
            }

    def replica_metrics(self, app_name: str | None = None,
                        deployment: str | None = None,
                        full_ids: bool = False) -> dict:
        """Per-replica metrics incl. the user callable's own stats()
        (e.g. the LLM engine's KV-cache hit/preempt counters and its
        prefix-cache summary) — the serve state API's detail surface
        (ray: serve application details' replica_details).  Fanned out
        OUTSIDE the lock: a slow replica must not wedge the control
        loop.  `deployment` narrows the fan-out to one deployment (the
        cache-aware router polls this per handle); `full_ids` keys
        replicas by their complete actor id so callers can join against
        membership from get_deployment_info."""
        import ray_tpu

        with self._lock:
            targets = []
            for an, app in self._apps.items():
                if app_name is not None and an != app_name:
                    continue
                for dname, st in app["deployments"].items():
                    if deployment is not None and dname != deployment:
                        continue
                    for rid, rec in st.replicas.items():
                        if rec["state"] == "RUNNING":
                            targets.append((an, dname, rid,
                                            rec["handle"]))
        out: dict = {}
        refs = []
        for an, dname, rid, handle in targets:
            try:
                refs.append((an, dname, rid,
                             handle.get_metrics.remote()))
            except Exception:  # noqa: BLE001 - replica mid-restart
                pass
        for an, dname, rid, ref in refs:
            try:
                m = ray_tpu.get(ref, timeout=5.0)
            except Exception:  # noqa: BLE001
                m = {"error": "unreachable"}
            key = rid if full_ids else rid[:12]
            out.setdefault(an, {}).setdefault(dname, {})[key] = m
        return out

    def get_app_routes(self) -> dict:
        """route_prefix -> (app, ingress deployment); polled by proxies
        (ray: long-poll route table push)."""
        with self._lock:
            return {app["route_prefix"]: (name, app["ingress"])
                    for name, app in self._apps.items()
                    if any(not st.deleting
                           for st in app["deployments"].values())}

    def status(self) -> dict:
        """Serve status tree (ray: serve.status / ServeStatusSchema)."""
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                deps = {}
                for name, st in app["deployments"].items():
                    running = sum(1 for r in st.replicas.values()
                                  if r["state"] == "RUNNING")
                    deps[name] = {
                        "status": ("DELETING" if st.deleting else
                                   "HEALTHY" if running >= st.target_replicas
                                   else "UPDATING"),
                        "replicas": running,
                        "target_replicas": st.target_replicas,
                    }
                alive = any(not st.deleting
                            for st in app["deployments"].values())
                out[app_name] = {
                    "status": "RUNNING" if alive and all(
                        d["status"] == "HEALTHY" for d in deps.values())
                    else "DELETING" if not alive else "DEPLOYING",
                    "route_prefix": app["route_prefix"],
                    "deployments": deps,
                }
            return out

    def graceful_shutdown(self) -> None:
        with self._lock:
            for app in self._apps.values():
                for st in app["deployments"].values():
                    st.deleting = True
                    st.target_replicas = 0

    def wait_for_deployments_ready(self, app_name: str,
                                   timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                app = self._apps.get(app_name)
                if app is not None:
                    states = [st for st in app["deployments"].values()
                              if not st.deleting]
                    if states and all(
                        sum(1 for r in st.replicas.values()
                            if r["state"] == "RUNNING") >= st.target_replicas
                            and st.target_replicas > 0
                            for st in states):
                        return True
            time.sleep(0.05)
        return False

    # --------------------------------------------------------- control loop
    def _state(self, app_name: str, deployment: str) -> _DeploymentState | None:
        app = self._apps.get(app_name)
        if app is None:
            return None
        return app["deployments"].get(deployment)

    def _run_control_loop(self) -> None:
        """ray: controller.py:372 run_control_loop."""
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001
                logger.error("reconcile error:\n%s", traceback.format_exc())
            time.sleep(RECONCILE_PERIOD_S)

    def _reconcile_once(self) -> None:
        try:
            self._reconcile_proxies()
        except Exception:  # noqa: BLE001
            logger.warning("proxy reconcile failed:\n%s",
                           traceback.format_exc())
        with self._lock:
            states = [st for app in self._apps.values()
                      for st in app["deployments"].values()]
        for st in states:
            # A state replaced by deploy_app mid-snapshot must not be
            # reconciled: starting replicas into it would leak actors.
            with self._lock:
                if st.superseded or self._state(st.app, st.name) is not st:
                    continue
            self._autoscale(st)
            self._reconcile_deployment(st)
        with self._lock:
            for app_name, app in list(self._apps.items()):
                for name, st in list(app["deployments"].items()):
                    if st.deleting and not st.replicas and not st.draining:
                        del app["deployments"][name]
                if not app["deployments"]:
                    del self._apps[app_name]

    # --------------------------------------------------------- proxies
    def _reconcile_proxies(self) -> None:
        """One ProxyActor per ALIVE node, pinned by hard node affinity,
        restarted when dead (ray: serve proxy_state.py reconciliation
        driven by the serve controller).  Throttled: membership changes
        rarely, and each sync costs two control-plane dumps."""
        now = time.monotonic()
        if now - getattr(self, "_last_proxy_sync", 0.0) < 2.0:
            return
        self._last_proxy_sync = now
        import ray_tpu
        from ray_tpu.utils.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        from ray_tpu.utils.state import list_actors

        alive_nodes = {n["node_id"] for n in ray_tpu.nodes()
                       if n.get("state") == "ALIVE"}
        live_proxies = {
            a["name"]: a for a in list_actors()
            if (a.get("name") or "").startswith("SERVE_PROXY::")
            and a.get("state") == "ALIVE"}
        from ray_tpu.serve.proxy import ProxyActor

        for node_id in alive_nodes:
            name = f"SERVE_PROXY::{node_id}"
            if name in live_proxies:
                continue
            try:
                ray_tpu.remote(ProxyActor).options(
                    name=name, get_if_exists=True, lifetime="detached",
                    max_concurrency=64, num_cpus=0,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id, soft=False),
                ).remote(self._controller_self_id(),
                         self._http_host, self._http_port)
            except Exception:  # noqa: BLE001
                logger.warning("proxy start on %s failed:\n%s",
                               node_id[:12], traceback.format_exc())

    def _controller_self_id(self) -> str:
        from ray_tpu.runtime_context import get_runtime_context

        return get_runtime_context().get_actor_id()

    def set_http_options(self, host: str, port: int) -> None:
        import ray_tpu

        changed = (host, port) != (self._http_host, self._http_port)
        self._http_host = host
        self._http_port = port
        if changed:
            # Existing proxies hold the old bind options: kill them so
            # the reconcile loop recreates them with the new ones.
            for name in self.list_proxies():
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:  # noqa: BLE001
                    pass

    def list_proxies(self) -> list[str]:
        from ray_tpu.utils.state import list_actors

        return sorted(a["name"] for a in list_actors()
                      if (a.get("name") or "").startswith("SERVE_PROXY::")
                      and a.get("state") == "ALIVE")

    def _autoscale(self, st: _DeploymentState) -> None:
        """Scale on total ongoing requests (ray: autoscaling_state.py;
        metric = replica-reported num_ongoing).  Probes are in-flight
        ObjectRefs collected on a later tick — never a long block."""
        cfg = st.config.autoscaling_config
        if cfg is None or st.deleting:
            return
        import ray_tpu

        if st.probe is not None:
            refs_recs, deadline = st.probe
            refs = [r for _, r in refs_recs]
            ready, _pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0)
            if len(ready) == len(refs) or time.monotonic() > deadline:
                total = 0.0
                for ref in ready:
                    try:
                        total += ray_tpu.get(ref, timeout=1.0)
                    except Exception:  # noqa: BLE001
                        pass
                st.probe = None
                self._apply_autoscale_decision(st, cfg, total,
                                               len(refs_recs))
            return
        with self._lock:
            running = [rec for rec in st.replicas.values()
                       if rec["state"] == "RUNNING"]
        if not running:
            return
        refs_recs = [(rec, rec["handle"].get_queue_len.remote())
                     for rec in running]
        st.probe = (refs_recs, time.monotonic() + 5.0)

    def _apply_autoscale_decision(self, st, cfg, total: float,
                                  n_running: int) -> None:
        desired = cfg.desired(total, n_running)
        now = time.monotonic()
        if desired > st.target_replicas:
            if now - st.last_scale_up >= cfg.upscale_delay_s:
                st.target_replicas = desired
                st.last_scale_up = now
        elif desired < st.target_replicas:
            if now - st.last_scale_down >= cfg.downscale_delay_s:
                st.target_replicas = desired
                st.last_scale_down = now
        else:
            st.last_scale_up = st.last_scale_down = now

    def _reconcile_deployment(self, st: _DeploymentState) -> None:
        """Start/stop replicas toward target; poll pending inits and
        health checks (ray: deployment_state.py update loop)."""
        self._poll_starting(st)
        self._poll_health(st)

        with self._lock:
            running = {rid: rec for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"}
            starting = sum(1 for rec in st.replicas.values()
                           if rec["state"] == "STARTING")
            n = len(running) + starting
            target = st.target_replicas
        if n < target:
            for _ in range(target - n):
                self._start_replica(st)
        elif len(running) > target:
            extra = list(running)[target - len(running):] if target else \
                list(running)
            for rid in extra[:len(running) - target]:
                self._remove_replica(st, rid, drain=True)
        # Rolling update: once the new version serves, retire the old.
        with self._lock:
            new_up = any(rec["state"] == "RUNNING"
                         for rec in st.replicas.values())
            drain_now = (list(st.draining.items())
                         if (new_up and len(running) >= target) or st.deleting
                         else [])
            for rid, _rec in drain_now:
                st.draining.pop(rid, None)
        for _rid, rec in drain_now:
            self._stop_replica(rec, drain=True,
                               timeout=st.config.graceful_shutdown_timeout_s)

    def _poll_starting(self, st: _DeploymentState) -> None:
        """Flip STARTING→RUNNING when the init probe resolves (non-blocking;
        ray: replica startup polling in deployment_state)."""
        import ray_tpu

        with self._lock:
            pending = [(rid, rec) for rid, rec in st.replicas.items()
                       if rec["state"] == "STARTING"]
        for rid, rec in pending:
            ready, _ = ray_tpu.wait([rec["init_ref"]], timeout=0)
            if ready:
                try:
                    ray_tpu.get(ready[0], timeout=1.0)
                    with self._lock:
                        rec["state"] = "RUNNING"
                        rec["last_health"] = time.monotonic()
                        st.membership_version += 1
                except Exception:  # noqa: BLE001
                    logger.error("replica init failed:\n%s",
                                 traceback.format_exc())
                    self._remove_replica(st, rid, drain=False)
            elif time.monotonic() > rec["init_deadline"]:
                logger.error("replica %s init timed out", rid[:12])
                self._remove_replica(st, rid, drain=False)

    def _poll_health(self, st: _DeploymentState) -> None:
        """Issue/collect health probes without blocking (ray:
        deployment_state health-check polling)."""
        import ray_tpu

        with self._lock:
            running = [(rid, rec) for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"]
        for rid, rec in running:
            ref = rec.get("health_ref")
            if ref is not None:
                ready, _ = ray_tpu.wait([ref], timeout=0)
                if ready:
                    rec["health_ref"] = None
                    try:
                        ray_tpu.get(ready[0], timeout=1.0)
                        rec["last_health"] = time.monotonic()
                    except Exception:  # noqa: BLE001
                        logger.warning(
                            "replica %s failed health check; replacing",
                            rid[:12])
                        self._remove_replica(st, rid, drain=False)
                elif time.monotonic() > rec["health_deadline"]:
                    logger.warning("replica %s health check timed out",
                                   rid[:12])
                    self._remove_replica(st, rid, drain=False)
            elif time.monotonic() - rec.get("last_health", 0) \
                    >= st.config.health_check_period_s:
                rec["health_ref"] = rec["handle"].check_health.remote()
                rec["health_deadline"] = time.monotonic() + \
                    st.config.health_check_timeout_s

    def _start_replica(self, st: _DeploymentState) -> None:
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        actor_opts = dict(st.config.ray_actor_options)
        actor_opts.setdefault("num_cpus", 0.1)
        actor_opts["max_concurrency"] = max(
            8, st.config.max_ongoing_requests + 2)
        try:
            handle = ray_tpu.remote(Replica).options(**actor_opts).remote(
                st.cls, st.init_args, st.init_kwargs,
                st.config.max_ongoing_requests, st.config.user_config,
                app_name=st.app, deployment=st.name)
        except Exception:  # noqa: BLE001
            logger.error("replica start failed:\n%s", traceback.format_exc())
            return
        rid = handle.actor_id
        init_ref = handle.check_health.remote()
        with self._lock:
            if st.superseded or self._state(st.app, st.name) is not st:
                # Lost a race with a redeploy: don't leak the actor.
                ray_tpu.kill(handle)
                return
            st.replicas[rid] = {
                "handle": handle, "state": "STARTING",
                "init_ref": init_ref,
                "init_deadline": time.monotonic() + REPLICA_INIT_TIMEOUT_S,
                "health_ref": None, "health_deadline": 0.0,
                "last_health": time.monotonic()}

    def _remove_replica(self, st: _DeploymentState, rid: str,
                        drain: bool) -> None:
        with self._lock:
            rec = st.replicas.pop(rid, None)
            st.membership_version += 1
        if rec is not None:
            rec["state"] = "STOPPING"
            self._stop_replica(rec, drain=drain,
                               timeout=st.config.graceful_shutdown_timeout_s)

    def _stop_replica(self, rec: dict, drain: bool = True,
                      timeout: float = 5.0) -> None:
        import ray_tpu

        if drain:
            try:
                ray_tpu.get(rec["handle"].prepare_for_shutdown.remote(),
                            timeout=timeout)
            except Exception:  # noqa: BLE001
                pass
        try:
            ray_tpu.kill(rec["handle"])
        except Exception:  # noqa: BLE001
            pass


def new_version() -> str:
    return uuid.uuid4().hex[:12]
