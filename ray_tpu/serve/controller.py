"""ServeController: the reconciling control plane for Serve.

Analog of ray: python/ray/serve/_private/controller.py (ServeController,
run_control_loop:372) + deployment_state.py (DeploymentState reconciler) +
autoscaling_state.py (autoscaling policy) + deployment_scheduler.py.

A *threaded* actor (not asyncio): the control loop and RPC methods run on
the actor's thread pool so they may freely make blocking framework calls
(create actor / get / kill) — the same reason the reference runs its
reconciler off the replica event loops.  Replica membership is versioned;
handles poll `get_deployment_info` (the long-poll analog of ray:
_private/long_poll.py LongPollHost).

Concurrency discipline: the controller lock only guards in-memory state —
no RPC is ever made while holding it.  Replica starts/health checks are
asynchronous (pending ObjectRefs polled each reconcile tick), so one slow
replica init never stalls reconciliation of other deployments (ray:
deployment_state starts replicas async and polls readiness).
"""
from __future__ import annotations

import logging
import threading
import time
import traceback
import uuid
from typing import Any

from ray_tpu import tracing
from ray_tpu.serve import slo

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
RECONCILE_PERIOD_S = 0.2
REPLICA_INIT_TIMEOUT_S = 120.0


class _DeploymentState:
    """Target spec + live replicas for one deployment (ray:
    deployment_state.py DeploymentState)."""

    def __init__(self, app: str, name: str, cls, init_args, init_kwargs,
                 config, version: str):
        self.app = app
        self.name = name
        self.cls = cls
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.config = config
        self.version = version
        self.target_replicas = config.num_replicas
        # replica actor_id -> {"handle", "state", "init_ref", "init_deadline",
        #                      "health_ref", "health_deadline", "last_health"}
        self.replicas: dict[str, dict] = {}
        # Old-version replicas still serving during a rolling code update;
        # advertised only until the new version is up (ray: gradual rollout).
        self.draining: dict[str, dict] = {}
        self.membership_version = 0
        self.last_scale_up = 0.0
        self.last_scale_down = 0.0
        self.deleting = False
        self.superseded = False   # replaced by a newer _DeploymentState
        # autoscale probe in flight: list of (rec, ref) + deadline
        self.probe: tuple[list, float] | None = None
        # Last completed metrics probe, merged: {total_ongoing,
        # p99_ttft_ms, p99_queue_ms, n, t} — the SLO loop's decision
        # input and the PD-rebalance pass's stage-split signal.
        self.slo_snapshot: dict | None = None


class ServeController:
    """Named detached actor; one per cluster (ray: controller.py:86)."""

    def __init__(self):
        self._lock = threading.RLock()
        # app -> {"route_prefix", "ingress", "deployments": {name: state}}
        self._apps: dict[str, dict] = {}
        self._http_host = "127.0.0.1"
        self._http_port = 0
        # SLO autoscaling kill-switch override (set_autoscale_enabled
        # RPC: same-run A/B without touching this process's env);
        # None = follow RAY_TPU_SERVE_AUTOSCALE.
        self._autoscale_override: bool | None = None
        # request_resources demand posting: re-post only when a target
        # changed (dirty) and at most every few seconds.
        self._demand_dirty = False
        self._last_demand_post = 0.0
        # (app, prefill_deployment) -> last pool-ratio shift time.
        self._last_pd_shift: dict[tuple, float] = {}
        # Tier-2 prefix-store directory (serve/prefix_store.py): hash →
        # demoted-subtree entries published by the replicas.  Scrubbed
        # with the app (delete_app) and with each dead replica.
        from ray_tpu.serve.prefix_store import StoreDirectory

        self._prefix_store = StoreDirectory()
        # Multi-LoRA adapter registry (serve/lora.py): model_id →
        # sealed-adapter object ref + version.  Cluster-scoped (an
        # adapter serves any lora-enabled deployment), cleared at
        # graceful_shutdown — the directory holds the primary refs.
        from ray_tpu.serve.lora import AdapterDirectory

        self._lora = AdapterDirectory()
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._run_control_loop, daemon=True, name="serve-ctrl")
        self._thread.start()

    # ------------------------------------------------------------ public RPC
    def deploy_app(self, app_name: str, route_prefix: str, ingress: str,
                   deployments: list[dict]) -> None:
        """Declarative (re)deploy of a whole app (ray: serve.run →
        controller.deploy_apps).  Never blocks on replica RPCs: code
        changes hand old replicas to the new state's drain list; config
        changes are applied by the reconcile loop."""
        reconfigures: list[tuple[Any, Any]] = []
        with self._lock:
            app = self._apps.setdefault(
                app_name, {"route_prefix": route_prefix, "ingress": ingress,
                           "deployments": {}})
            app["route_prefix"] = route_prefix
            app["ingress"] = ingress
            new_names = {d["name"] for d in deployments}
            for name, st in list(app["deployments"].items()):
                if name not in new_names:
                    st.deleting = True
                    st.target_replicas = 0
            for d in deployments:
                cur = app["deployments"].get(d["name"])
                if cur is not None and cur.version == d["version"] \
                        and not cur.deleting:
                    # Config-only change: rescale/reconfigure in place
                    # (ray: deployment_state config-change classification).
                    old_user_config = cur.config.user_config
                    cur.config = d["config"]
                    if cur.config.autoscaling_config is None:
                        cur.target_replicas = d["config"].num_replicas
                    if d["config"].user_config is not None and \
                            d["config"].user_config != old_user_config:
                        reconfigures.append((cur, d["config"].user_config))
                    continue
                new_st = _DeploymentState(
                    app_name, d["name"], d["cls"], d["init_args"],
                    d["init_kwargs"], d["config"], d["version"])
                if cur is not None:
                    cur.superseded = True
                    # Old replicas keep serving until the new version is up.
                    new_st.draining.update(cur.replicas)
                    new_st.draining.update(cur.draining)
                app["deployments"][d["name"]] = new_st
            # Post the INITIAL demand floor too: a fresh deploy whose
            # min_replicas exceed current capacity needs nodes before
            # any scale decision ever changes a target.
            self._demand_dirty = True
        for st, user_config in reconfigures:
            self._reconfigure_in_place(st, user_config)

    def _reconfigure_in_place(self, st: _DeploymentState, user_config) -> None:
        import ray_tpu

        with self._lock:
            handles = [rec["handle"] for rec in st.replicas.values()
                       if rec["state"] == "RUNNING"]
        refs = [h.reconfigure.remote(user_config) for h in handles]
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=30.0)
            except Exception:  # noqa: BLE001
                logger.warning("reconfigure failed:\n%s",
                               traceback.format_exc())

    def delete_app(self, app_name: str) -> None:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return
            for st in app["deployments"].values():
                st.deleting = True
                st.target_replicas = 0
        # The deleted app's autoscaler demand floor must shrink too,
        # and its demoted prefix entries must not outlive it (the
        # directory's borrowed refs go here; the replicas drop their
        # primary refs in LLMServer.shutdown during drain).
        self._prefix_store.drop_app(app_name)
        self._demand_dirty = True

    def get_deployment_info(self, app_name: str, deployment: str) -> dict:
        with self._lock:
            st = self._state(app_name, deployment)
            if st is None:
                return {"version": -1, "replicas": [], "max_ongoing": 0}
            running = [rid for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"]
            if not running:
                # During a rolling update the old version keeps serving.
                running = [rid for rid, rec in st.draining.items()
                           if rec["state"] == "RUNNING"]
            return {
                "version": st.membership_version,
                "replicas": running,
                "max_ongoing": st.config.max_ongoing_requests,
            }

    def replica_metrics(self, app_name: str | None = None,
                        deployment: str | None = None,
                        full_ids: bool = False) -> dict:
        """Per-replica metrics incl. the user callable's own stats()
        (e.g. the LLM engine's KV-cache hit/preempt counters and its
        prefix-cache summary) — the serve state API's detail surface
        (ray: serve application details' replica_details).  Fanned out
        OUTSIDE the lock: a slow replica must not wedge the control
        loop.  `deployment` narrows the fan-out to one deployment (the
        cache-aware router polls this per handle); `full_ids` keys
        replicas by their complete actor id so callers can join against
        membership from get_deployment_info."""
        import ray_tpu

        with self._lock:
            targets = []
            for an, app in self._apps.items():
                if app_name is not None and an != app_name:
                    continue
                for dname, st in app["deployments"].items():
                    if deployment is not None and dname != deployment:
                        continue
                    for rid, rec in st.replicas.items():
                        if rec["state"] == "RUNNING":
                            targets.append((an, dname, rid,
                                            rec["handle"]))
        out: dict = {}
        refs = []
        for an, dname, rid, handle in targets:
            try:
                refs.append((an, dname, rid,
                             handle.get_metrics.remote()))
            except Exception:  # noqa: BLE001 - replica mid-restart
                pass
        for an, dname, rid, ref in refs:
            try:
                m = ray_tpu.get(ref, timeout=5.0)
            except Exception:  # noqa: BLE001
                m = {"error": "unreachable"}
            key = rid if full_ids else rid[:12]
            out.setdefault(an, {}).setdefault(dname, {})[key] = m
        return out

    # --------------------------------------------- prefix-store verbs
    # Thin RPC surface over the StoreDirectory (serve/prefix_store.py):
    # replicas publish/withdraw demoted subtrees, the miss path looks
    # up the deepest stored prefix, and handles poll the summary for
    # store-aware routing.  All logic lives in the directory.
    def prefix_store_publish(self, app: str, meta: dict, ref) -> bool:
        return self._prefix_store.publish(app, meta, ref)

    def prefix_store_lookup(self, app: str, hashes: list, page: int,
                            seed, weight_version: int | None = None,
                            min_depth: int = 0):
        return self._prefix_store.lookup(
            app, hashes, page, seed, weight_version=weight_version,
            min_depth=min_depth)

    def prefix_store_forget(self, app: str, replica: str | None = None,
                            below_version: int | None = None,
                            hashes: list | None = None) -> int:
        return self._prefix_store.forget(
            app, replica=replica, below_version=below_version,
            hashes=hashes)

    def prefix_store_summary(self, app: str) -> dict:
        return self._prefix_store.summary(app)

    def prefix_store_stats(self) -> dict:
        return self._prefix_store.stats()

    # ------------------------------------------------ multi-LoRA verbs
    # Thin RPC surface over the AdapterDirectory (serve/lora.py):
    # drivers publish/withdraw adapters, replicas look them up for the
    # page-in miss path.  All logic lives in the directory.
    def lora_publish(self, model_id: str, meta: dict, ref) -> dict:
        return self._lora.publish(model_id, meta, ref)

    def lora_lookup(self, model_id: str):
        return self._lora.lookup(model_id)

    def lora_forget(self, model_id: str) -> bool:
        return self._lora.forget(model_id)

    def lora_summary(self) -> dict:
        return self._lora.summary()

    def lora_stats(self) -> dict:
        return self._lora.stats()

    def get_app_routes(self) -> dict:
        """route_prefix -> (app, ingress deployment); polled by proxies
        (ray: long-poll route table push)."""
        with self._lock:
            return {app["route_prefix"]: (name, app["ingress"])
                    for name, app in self._apps.items()
                    if any(not st.deleting
                           for st in app["deployments"].values())}

    def status(self) -> dict:
        """Serve status tree (ray: serve.status / ServeStatusSchema)."""
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                deps = {}
                for name, st in app["deployments"].items():
                    running = sum(1 for r in st.replicas.values()
                                  if r["state"] == "RUNNING")
                    deps[name] = {
                        "status": ("DELETING" if st.deleting else
                                   "HEALTHY" if running >= st.target_replicas
                                   else "UPDATING"),
                        "replicas": running,
                        "target_replicas": st.target_replicas,
                    }
                alive = any(not st.deleting
                            for st in app["deployments"].values())
                out[app_name] = {
                    "status": "RUNNING" if alive and all(
                        d["status"] == "HEALTHY" for d in deps.values())
                    else "DELETING" if not alive else "DEPLOYING",
                    "route_prefix": app["route_prefix"],
                    "deployments": deps,
                }
            return out

    def graceful_shutdown(self) -> None:
        with self._lock:
            for app in self._apps.values():
                for st in app["deployments"].values():
                    st.deleting = True
                    st.target_replicas = 0
        self._prefix_store.clear()
        # Published adapters die with serve (the directory holds their
        # primary refs — dropping the entries releases the arena bytes).
        self._lora.clear()
        # Clear the serve demand floor SYNCHRONOUSLY: serve.shutdown
        # kills this actor within seconds — the throttled reconcile
        # re-post may never run, and a stale floor would make the
        # cluster autoscaler hold nodes for phantom replicas forever.
        try:
            from ray_tpu.autoscaler import request_resources

            request_resources(bundles=[], requester="serve")
        except Exception:  # noqa: BLE001 - no autoscaler wired
            pass

    def wait_for_deployments_ready(self, app_name: str,
                                   timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                app = self._apps.get(app_name)
                if app is not None:
                    states = [st for st in app["deployments"].values()
                              if not st.deleting]
                    if states and all(
                        sum(1 for r in st.replicas.values()
                            if r["state"] == "RUNNING") >= st.target_replicas
                            and st.target_replicas > 0
                            for st in states):
                        return True
            time.sleep(0.05)
        return False

    # --------------------------------------------------------- control loop
    def _state(self, app_name: str, deployment: str) -> _DeploymentState | None:
        app = self._apps.get(app_name)
        if app is None:
            return None
        return app["deployments"].get(deployment)

    def _run_control_loop(self) -> None:
        """ray: controller.py:372 run_control_loop."""
        while not self._shutdown.is_set():
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001
                logger.error("reconcile error:\n%s", traceback.format_exc())
            time.sleep(RECONCILE_PERIOD_S)

    def _reconcile_once(self) -> None:
        try:
            self._reconcile_proxies()
        except Exception:  # noqa: BLE001
            logger.warning("proxy reconcile failed:\n%s",
                           traceback.format_exc())
        with self._lock:
            states = [st for app in self._apps.values()
                      for st in app["deployments"].values()]
        for st in states:
            # A state replaced by deploy_app mid-snapshot must not be
            # reconciled: starting replicas into it would leak actors.
            with self._lock:
                if st.superseded or self._state(st.app, st.name) is not st:
                    continue
            self._autoscale(st)
            self._reconcile_deployment(st)
        if self._autoscale_enabled():
            self._maybe_rebalance_pd()
        # Demand posting runs even with autoscaling disabled: a floor
        # posted while enabled must still SHRINK when the switch flips
        # off or an app is deleted — otherwise the autoscaler would
        # hold nodes for replicas that no longer exist.
        self._post_autoscaler_demand()
        self._memory_observe()
        with self._lock:
            for app_name, app in list(self._apps.items()):
                for name, st in list(app["deployments"].items()):
                    if st.deleting and not st.replicas and not st.draining:
                        del app["deployments"][name]
                if not app["deployments"]:
                    del self._apps[app_name]

    # ---------------------------------------------- memory observability
    def _memory_observe(self) -> None:
        """Memory-ledger leg of the reconcile loop (throttled): publish
        the per-deployment tier-2 prefix bytes gauge, and flag directory
        entries whose publishing replica this controller no longer
        knows — their arena objects died with the publisher, so every
        lookup against them can only fail (the sentinel alarm; the
        lazy dead-publisher scrub on the fetch path still does the
        cleanup)."""
        now = time.monotonic()
        if now - getattr(self, "_last_mem_observe", 0.0) < 5.0:
            return
        self._last_mem_observe = now
        try:
            from ray_tpu.utils import metrics as um

            g = um.get_or_create(
                um.Gauge, "serve_prefix_tier2_bytes",
                "Tier-2 prefix-store bytes per deployment",
                tag_keys=("app", "deployment"))
            per = self._prefix_store.bytes_by_deployment()
            # Zero removed series explicitly — gauges have no TTL, and
            # a deleted app must not read as still holding bytes.
            for app, dep in getattr(self, "_tier2_keys", set()) - \
                    set(per):
                g.set(0.0, tags={"app": app, "deployment": dep})
            for (app, dep), b in per.items():
                g.set(float(b), tags={"app": app, "deployment": dep})
            self._tier2_keys = set(per)
        except Exception:  # noqa: BLE001 - metrics must never stall
            pass           # the reconciler
        with self._lock:
            live = {rid for app in self._apps.values()
                    for st in app["deployments"].values()
                    for rid in (*st.replicas, *st.draining)}
        orphan = self._prefix_store.replicas() - live
        warned = getattr(self, "_tier2_orphan_warned", set())
        for rid in orphan - warned:
            t = time.time()
            tracing.emit("memory.leak", t, t, attrs={
                "kind": "tier2_orphan_publisher", "replica": rid})
            logger.warning(
                "leak sentinel: tier-2 prefix entries from unknown "
                "replica %s (publisher gone — entries are "
                "unreachable)", rid)
        self._tier2_orphan_warned = warned | orphan

    # --------------------------------------------------------- proxies
    def _reconcile_proxies(self) -> None:
        """One ProxyActor per ALIVE node, pinned by hard node affinity,
        restarted when dead (ray: serve proxy_state.py reconciliation
        driven by the serve controller).  Throttled: membership changes
        rarely, and each sync costs two control-plane dumps."""
        now = time.monotonic()
        if now - getattr(self, "_last_proxy_sync", 0.0) < 2.0:
            return
        self._last_proxy_sync = now
        import ray_tpu
        from ray_tpu.utils.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        from ray_tpu.utils.state import list_actors

        alive_nodes = {n["node_id"] for n in ray_tpu.nodes()
                       if n.get("state") == "ALIVE"}
        live_proxies = {
            a["name"]: a for a in list_actors()
            if (a.get("name") or "").startswith("SERVE_PROXY::")
            and a.get("state") == "ALIVE"}
        from ray_tpu.serve.proxy import ProxyActor

        for node_id in alive_nodes:
            name = f"SERVE_PROXY::{node_id}"
            if name in live_proxies:
                continue
            try:
                ray_tpu.remote(ProxyActor).options(
                    name=name, get_if_exists=True, lifetime="detached",
                    max_concurrency=64, num_cpus=0,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id, soft=False),
                ).remote(self._controller_self_id(),
                         self._http_host, self._http_port)
            except Exception:  # noqa: BLE001
                logger.warning("proxy start on %s failed:\n%s",
                               node_id[:12], traceback.format_exc())

    def _controller_self_id(self) -> str:
        from ray_tpu.runtime_context import get_runtime_context

        return get_runtime_context().get_actor_id()

    def set_http_options(self, host: str, port: int) -> None:
        import ray_tpu

        changed = (host, port) != (self._http_host, self._http_port)
        self._http_host = host
        self._http_port = port
        if changed:
            # Existing proxies hold the old bind options: kill them so
            # the reconcile loop recreates them with the new ones.
            for name in self.list_proxies():
                try:
                    ray_tpu.kill(ray_tpu.get_actor(name))
                except Exception:  # noqa: BLE001
                    pass

    def list_proxies(self) -> list[str]:
        from ray_tpu.utils.state import list_actors

        return sorted(a["name"] for a in list_actors()
                      if (a.get("name") or "").startswith("SERVE_PROXY::")
                      and a.get("state") == "ALIVE")

    def _autoscale_enabled(self) -> bool:
        """RAY_TPU_SERVE_AUTOSCALE kill switch, overridable live via
        the set_autoscale_enabled RPC (same-run A/B: the env of a
        long-lived controller actor can't be flipped from a driver)."""
        if self._autoscale_override is not None:
            return self._autoscale_override
        return slo.autoscale_on()

    def set_autoscale_enabled(self, on: bool | None) -> None:
        """None = follow the env switch; True/False = force."""
        self._autoscale_override = on

    def _autoscale(self, st: _DeploymentState) -> None:
        """The SLO loop: scale on ongoing-request load AND p99
        TTFT / queue-wait attainment (ray: autoscaling_state.py scales
        on ongoing only; the SLO terms consume the same per-replica
        latency windows that feed the stage histograms through
        replica_metrics).  Probes are in-flight ObjectRefs collected on
        a later tick — never a long block."""
        cfg = st.config.autoscaling_config
        if cfg is None or st.deleting or not self._autoscale_enabled():
            return
        import ray_tpu

        if st.probe is not None:
            refs_recs, deadline = st.probe
            refs = [r for _, r in refs_recs]
            ready, _pending = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0)
            if len(ready) == len(refs) or time.monotonic() > deadline:
                total = 0.0
                ttft: list[float] = []
                queuew: list[float] = []
                for ref in ready:
                    try:
                        m = ray_tpu.get(ref, timeout=1.0)
                    except Exception:  # noqa: BLE001
                        continue
                    if isinstance(m, (int, float)):
                        total += m          # legacy queue-len probe
                        continue
                    if not isinstance(m, dict):
                        continue
                    total += m.get("num_ongoing", 0)
                    qw = (m.get("queue_wait_ms") or {}).get("p99")
                    if qw is not None:
                        queuew.append(qw)
                    s = (m.get("user_stats") or {}).get("slo") or {}
                    t = (s.get("ttft_ms") or {}).get("p99")
                    if t is not None:
                        ttft.append(t)
                    q2 = (s.get("queue_ms") or {}).get("p99")
                    if q2 is not None:
                        queuew.append(q2)
                st.probe = None
                # Tail attainment is per-request, not per-replica:
                # the WORST replica's p99 is the deployment's p99 bound.
                st.slo_snapshot = {
                    "total_ongoing": total,
                    "p99_ttft_ms": max(ttft) if ttft else None,
                    "p99_queue_ms": max(queuew) if queuew else None,
                    "n": len(refs_recs), "t": time.monotonic()}
                self._apply_autoscale_decision(st, cfg, total,
                                               len(refs_recs))
            return
        with self._lock:
            running = [rec for rec in st.replicas.values()
                       if rec["state"] == "RUNNING"]
        if not running:
            return
        refs_recs = [(rec, rec["handle"].get_metrics.remote())
                     for rec in running]
        st.probe = (refs_recs, time.monotonic() + 5.0)

    def _apply_autoscale_decision(self, st, cfg, total: float,
                                  n_running: int) -> None:
        snap = st.slo_snapshot or {}
        desired, reason = slo.slo_desired(
            cfg, n_running, total, snap.get("p99_ttft_ms"),
            snap.get("p99_queue_ms"))
        now = time.monotonic()
        prev = st.target_replicas
        if desired > st.target_replicas:
            if now - st.last_scale_up >= cfg.upscale_delay_s:
                st.target_replicas = desired
                st.last_scale_up = now
        elif desired < st.target_replicas:
            if now - st.last_scale_down >= cfg.downscale_delay_s:
                st.target_replicas = desired
                st.last_scale_down = now
        else:
            st.last_scale_up = st.last_scale_down = now
        if st.target_replicas != prev:
            # Flight-recorder span: WHY capacity changed, with the
            # metrics that drove it (a trace of the spike shows the
            # breach → scale → recovery chain).
            if tracing.ENABLED:
                tracing.emit(
                    "serve.scale", time.time(),
                    attrs={"app": st.app, "deployment": st.name,
                           "from": prev, "to": st.target_replicas,
                           "reason": reason,
                           "total_ongoing": round(total, 1),
                           "p99_ttft_ms": snap.get("p99_ttft_ms"),
                           "p99_queue_ms": snap.get("p99_queue_ms")})
            self._demand_dirty = True

    def _post_autoscaler_demand(self) -> None:
        """Post the autoscaled deployments' aggregate replica demand as
        a request_resources floor (requester-scoped: never clobbers
        elastic training's demand) so the autoscaler v2 reconciler
        provisions nodes for replicas the cluster can't place yet.
        Throttled: re-posts only after a target changed, at most every
        2s.  Best-effort — no autoscaler, no harm."""
        now = time.monotonic()
        if not self._demand_dirty or now - self._last_demand_post < 2.0:
            return
        self._demand_dirty = False
        self._last_demand_post = now
        bundles = []
        with self._lock:
            for app in self._apps.values():
                for st in app["deployments"].values():
                    if st.config.autoscaling_config is None \
                            or st.deleting:
                        continue
                    cpu = st.config.ray_actor_options.get(
                        "num_cpus", 0.1)
                    bundles.extend({"CPU": cpu}
                                   for _ in range(st.target_replicas))
        try:
            from ray_tpu.autoscaler import request_resources

            request_resources(bundles=bundles, requester="serve")
        except Exception:  # noqa: BLE001 - no autoscaler wired
            pass

    def _maybe_rebalance_pd(self) -> None:
        """Prefill:decode pool-ratio knob for disaggregated LLM apps:
        shift ONE replica of budget from the underloaded pool to the
        overloaded one when the stage split says so (serve/slo.py
        pd_rebalance) — a knob no single-pool autoscaler has, because
        it needs the prefill-vs-decode stage attribution.  Cooldown
        10s per edge; both pools must be autoscaled and have fresh
        probe snapshots."""
        with self._lock:
            edges = []
            for app_name, app in self._apps.items():
                deps = app["deployments"]
                for name, st in deps.items():
                    kw = st.init_kwargs or {}
                    if kw.get("role") != "prefill":
                        continue
                    dd = kw.get("decode_deployment")
                    dd = getattr(dd, "deployment_name", dd)
                    dst = deps.get(dd) if isinstance(dd, str) else None
                    if dst is not None:
                        edges.append((app_name, name, st, dst))
        now = time.monotonic()
        for app_name, name, pre, dec in edges:
            pcfg, dcfg = pre.config.autoscaling_config, \
                dec.config.autoscaling_config
            if pcfg is None or dcfg is None or pre.deleting \
                    or dec.deleting:
                continue
            psnap, dsnap = pre.slo_snapshot, dec.slo_snapshot
            if not psnap or not dsnap:
                continue
            # Freshness + zero-load gates (the slo_desired discipline):
            # a stale or idle-app snapshot's p99 tail must not churn
            # pool budget after traffic stops.
            if min(psnap.get("t", 0.0), dsnap.get("t", 0.0)) \
                    < now - 10.0:
                continue
            if psnap.get("total_ongoing", 0) \
                    + dsnap.get("total_ongoing", 0) <= 0:
                continue
            if now - self._last_pd_shift.get((app_name, name), 0.0) \
                    < 10.0:
                continue
            shift = slo.pd_rebalance(psnap, dsnap, pre.target_replicas,
                                     dec.target_replicas, pcfg, dcfg)
            if not shift:
                continue
            src, dst = (pre, dec) if shift > 0 else (dec, pre)
            with self._lock:
                src.target_replicas -= 1
                dst.target_replicas += 1
                # Cooldown stamps that keep the shift from being
                # immediately REVERTED by the per-pool loop: the source
                # must not upscale straight back (last_scale_up) and
                # the destination must not downscale straight back
                # (last_scale_down).
                src.last_scale_up = dst.last_scale_down = now
            self._last_pd_shift[(app_name, name)] = now
            self._demand_dirty = True
            if tracing.ENABLED:
                tracing.emit(
                    "serve.pd_rebalance", time.time(),
                    attrs={"app": app_name, "prefill": pre.name,
                           "decode": dec.name,
                           "shift": "prefill->decode" if shift > 0
                           else "decode->prefill",
                           "prefill_p99_queue_ms":
                           psnap.get("p99_queue_ms"),
                           "decode_p99_queue_ms":
                           dsnap.get("p99_queue_ms"),
                           "prefill_target": pre.target_replicas,
                           "decode_target": dec.target_replicas})

    def _reconcile_deployment(self, st: _DeploymentState) -> None:
        """Start/stop replicas toward target; poll pending inits and
        health checks (ray: deployment_state.py update loop)."""
        self._poll_starting(st)
        self._poll_health(st)

        with self._lock:
            running = {rid: rec for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"}
            starting = sum(1 for rec in st.replicas.values()
                           if rec["state"] == "STARTING")
            n = len(running) + starting
            target = st.target_replicas
        if n < target:
            for _ in range(target - n):
                self._start_replica(st)
        elif len(running) > target:
            extra = list(running)[target - len(running):] if target else \
                list(running)
            for rid in extra[:len(running) - target]:
                self._remove_replica(st, rid, drain=True)
        # Rolling update: once the new version serves, retire the old.
        with self._lock:
            new_up = any(rec["state"] == "RUNNING"
                         for rec in st.replicas.values())
            drain_now = (list(st.draining.items())
                         if (new_up and len(running) >= target) or st.deleting
                         else [])
            for rid, _rec in drain_now:
                st.draining.pop(rid, None)
        for _rid, rec in drain_now:
            self._stop_replica(rec, drain=True,
                               timeout=st.config.graceful_shutdown_timeout_s)

    def _poll_starting(self, st: _DeploymentState) -> None:
        """Flip STARTING→RUNNING when the init probe resolves (non-blocking;
        ray: replica startup polling in deployment_state)."""
        import ray_tpu

        with self._lock:
            pending = [(rid, rec) for rid, rec in st.replicas.items()
                       if rec["state"] == "STARTING"]
        for rid, rec in pending:
            ready, _ = ray_tpu.wait([rec["init_ref"]], timeout=0)
            if ready:
                try:
                    ray_tpu.get(ready[0], timeout=1.0)
                    with self._lock:
                        rec["state"] = "RUNNING"
                        rec["last_health"] = time.monotonic()
                        st.membership_version += 1
                except Exception:  # noqa: BLE001
                    logger.error("replica init failed:\n%s",
                                 traceback.format_exc())
                    self._remove_replica(st, rid, drain=False)
            elif time.monotonic() > rec["init_deadline"]:
                logger.error("replica %s init timed out", rid[:12])
                self._remove_replica(st, rid, drain=False)

    def _poll_health(self, st: _DeploymentState) -> None:
        """Issue/collect health probes without blocking (ray:
        deployment_state health-check polling)."""
        import ray_tpu

        with self._lock:
            running = [(rid, rec) for rid, rec in st.replicas.items()
                       if rec["state"] == "RUNNING"]
        for rid, rec in running:
            ref = rec.get("health_ref")
            if ref is not None:
                ready, _ = ray_tpu.wait([ref], timeout=0)
                if ready:
                    rec["health_ref"] = None
                    try:
                        ray_tpu.get(ready[0], timeout=1.0)
                        rec["last_health"] = time.monotonic()
                    except Exception:  # noqa: BLE001
                        logger.warning(
                            "replica %s failed health check; replacing",
                            rid[:12])
                        self._remove_replica(st, rid, drain=False)
                elif time.monotonic() > rec["health_deadline"]:
                    logger.warning("replica %s health check timed out",
                                   rid[:12])
                    self._remove_replica(st, rid, drain=False)
            elif time.monotonic() - rec.get("last_health", 0) \
                    >= st.config.health_check_period_s:
                rec["health_ref"] = rec["handle"].check_health.remote()
                rec["health_deadline"] = time.monotonic() + \
                    st.config.health_check_timeout_s

    def _start_replica(self, st: _DeploymentState) -> None:
        import ray_tpu
        from ray_tpu.serve.replica import Replica

        actor_opts = dict(st.config.ray_actor_options)
        actor_opts.setdefault("num_cpus", 0.1)
        actor_opts["max_concurrency"] = max(
            8, st.config.max_ongoing_requests + 2)
        try:
            handle = ray_tpu.remote(Replica).options(**actor_opts).remote(
                st.cls, st.init_args, st.init_kwargs,
                st.config.max_ongoing_requests, st.config.user_config,
                app_name=st.app, deployment=st.name,
                max_queued_requests=getattr(
                    st.config, "max_queued_requests", -1))
        except Exception:  # noqa: BLE001
            logger.error("replica start failed:\n%s", traceback.format_exc())
            return
        rid = handle.actor_id
        init_ref = handle.check_health.remote()
        with self._lock:
            if st.superseded or self._state(st.app, st.name) is not st:
                # Lost a race with a redeploy: don't leak the actor.
                ray_tpu.kill(handle)
                return
            st.replicas[rid] = {
                "handle": handle, "state": "STARTING",
                "init_ref": init_ref,
                "init_deadline": time.monotonic() + REPLICA_INIT_TIMEOUT_S,
                "health_ref": None, "health_deadline": 0.0,
                "last_health": time.monotonic()}

    def _remove_replica(self, st: _DeploymentState, rid: str,
                        drain: bool) -> None:
        with self._lock:
            rec = st.replicas.pop(rid, None)
            st.membership_version += 1
        # A removed replica's demoted prefix entries are doomed (its
        # arena objects die with the owning process — every future pull
        # would fail): scrub them so lookups don't chase dead refs.
        # Drained replicas withdraw themselves too (LLMServer.shutdown);
        # this covers crashes and health-check kills.
        self._prefix_store.forget(st.app, replica=rid)
        if rec is not None:
            rec["state"] = "STOPPING"
            self._stop_replica(rec, drain=drain,
                               timeout=st.config.graceful_shutdown_timeout_s)

    def _stop_replica(self, rec: dict, drain: bool = True,
                      timeout: float = 5.0) -> None:
        import ray_tpu

        if drain:
            try:
                ray_tpu.get(rec["handle"].prepare_for_shutdown.remote(),
                            timeout=timeout)
            except Exception:  # noqa: BLE001
                pass
        try:
            ray_tpu.kill(rec["handle"])
        except Exception:  # noqa: BLE001
            pass


def new_version() -> str:
    return uuid.uuid4().hex[:12]
