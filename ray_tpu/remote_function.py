"""RemoteFunction: the object @ray_tpu.remote wraps a function into.

Analog of ray: python/ray/remote_function.py (RemoteFunction, _remote:266).
"""
from __future__ import annotations

import functools
from typing import Any, Callable

_OPTION_KEYS = {
    "num_cpus", "num_tpus", "num_returns", "resources", "max_retries",
    "retry_exceptions", "name", "scheduling_strategy", "placement_group",
    "placement_group_bundle_index", "runtime_env", "memory",
}


def validate_options(opts: dict) -> None:
    """ray: python/ray/_private/ray_option_utils.py validation table."""
    for k in opts:
        if k not in _OPTION_KEYS:
            raise ValueError(f"unknown option {k!r}; valid: {sorted(_OPTION_KEYS)}")
    if "num_returns" in opts and opts["num_returns"] is not None:
        nr = opts["num_returns"]
        if nr == "dynamic":
            return      # generator task: one ref resolving to N item refs
        if nr == "streaming":
            return      # generator task: items stream back as produced
        if not isinstance(nr, int) or nr < 0:
            raise ValueError('num_returns must be a non-negative int, '
                             '"dynamic", or "streaming"')


def resolve_pg_options(opts: dict) -> dict:
    """Translate placement-group / scheduling-strategy options into the
    internal bundle_key the agent's resource pools understand."""
    out = dict(opts)
    strategy = out.pop("scheduling_strategy", None)
    pg = out.pop("placement_group", None)
    idx = out.pop("placement_group_bundle_index", -1)
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        idx = getattr(strategy, "placement_group_bundle_index", -1) or -1
    elif strategy is not None and hasattr(strategy, "node_id"):
        out["affinity_node_id"] = strategy.node_id
        out["affinity_soft"] = bool(getattr(strategy, "soft", False))
    elif strategy is not None and hasattr(strategy, "hard"):
        # NodeLabelSchedulingStrategy (constraints already lowered).
        if strategy.hard:
            out["label_hard"] = strategy.hard
        if strategy.soft:
            out["label_soft"] = strategy.soft
    if pg is not None:
        out["pg_id"] = pg.id
        out["bundle_index"] = idx
        out["bundle_key"] = f"{pg.id}:{max(idx, 0)}"
    return out


class RemoteFunction:
    def __init__(self, fn: Callable, **default_options):
        validate_options(default_options)
        self._function = fn
        self._default_options = default_options
        functools.update_wrapper(self, fn)

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_options)

    def options(self, **options) -> "RemoteFunction":
        validate_options(options)
        merged = {**self._default_options, **options}
        clone = RemoteFunction(self._function, **{})
        clone._default_options = merged
        return clone

    def _remote(self, args: tuple, kwargs: dict, opts: dict):
        from ray_tpu import client as client_mod
        from ray_tpu._private.worker import global_worker

        if client_mod._ctx is not None:
            return client_mod._ctx.submit_function(self._function, args,
                                                   kwargs, opts)
        options = resolve_pg_options(opts)
        if options.get("placement_group") == "default":
            options.pop("placement_group")
        core = global_worker()
        if "pg_id" in options:
            _wait_pg_ready(core, options["pg_id"])
        if options.get("num_returns") == "dynamic":
            # One return ref whose value is an ObjectRefGenerator over the
            # yielded items (ray: num_returns="dynamic").
            options = {**options, "num_returns": 1, "dynamic": True}
            return core.submit_task(self._function, args, kwargs,
                                    options)[0]
        if options.get("num_returns") == "streaming":
            # Items stream back as produced (ray: ObjectRefGenerator);
            # returns the generator immediately.
            return core.submit_streaming_task(self._function, args,
                                              kwargs, options)
        refs = core.submit_task(self._function, args, kwargs, options)
        n = options.get("num_returns", 1)
        if n == 1:
            return refs[0]
        return refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            "remote functions cannot be called directly; use "
            f"{getattr(self._function, '__name__', 'fn')}.remote()")

    def bind(self, *args, **kwargs):
        """Lazy DAG node instead of immediate submission (ray:
        dag/function_node.py via remote_function.bind)."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __repr__(self):
        return f"RemoteFunction({getattr(self._function, '__name__', '?')})"


def _wait_pg_ready(core, pg_id: str) -> None:
    reply, _ = core.call(
        core.controller_addr, "pg_ready",
        {"pg_id": pg_id, "wait": True, "timeout": 120.0}, timeout=150.0)
    if reply.get("state") != "CREATED":
        raise RuntimeError(f"placement group {pg_id[:12]} not ready: "
                           f"{reply.get('state')}")
