"""joblib backend: `with joblib.parallel_backend("ray_tpu")` runs
sklearn/joblib workloads as cluster tasks.

Analog of ray: python/ray/util/joblib/ (register_ray +
ray_backend.RayBackend over Ray's multiprocessing Pool).  Same shape:
a joblib ParallelBackendBase whose effective_n_jobs is the cluster CPU
count and whose apply_async ships batches as remote tasks.
"""
from __future__ import annotations

import ray_tpu


def register_ray_tpu() -> None:
    """Register the 'ray_tpu' joblib backend (ray: register_ray())."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)


class _Result:
    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback

    def get(self, timeout: float | None = None):
        result = ray_tpu.get(self._ref, timeout=timeout)
        if self._callback:
            self._callback(result)
        return result


try:
    from joblib._parallel_backends import ParallelBackendBase as _Base
except Exception:  # noqa: BLE001 - joblib absent: class still importable
    _Base = object


class RayTpuBackend(_Base):
    """joblib ParallelBackendBase implementation over remote tasks."""

    supports_timeout = True
    supports_sharedmem = False
    supports_retrieve_callback = False
    default_n_jobs = -1

    def __init__(self, **kw):
        if _Base is not object:
            super().__init__(**kw)
        self.parallel = None
        self._task = None

    # -- joblib backend protocol -------------------------------------------
    def configure(self, n_jobs: int = 1, parallel=None, **_kw) -> int:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs: int) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 in Parallel has no meaning")
        cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if n_jobs is None or n_jobs < 0:
            return cpus
        return min(n_jobs, cpus)

    def apply_async(self, func, callback=None) -> _Result:
        if self._task is None:
            @ray_tpu.remote
            def _run_joblib_batch(batch):
                return batch()
            self._task = _run_joblib_batch
        return _Result(self._task.remote(func), callback)

    # joblib >= 1.4 name for apply_async
    def submit(self, func, callback=None) -> _Result:
        return self.apply_async(func, callback)

    def get_nested_backend(self):
        from joblib._parallel_backends import SequentialBackend

        return SequentialBackend(nesting_level=1), None

    def abort_everything(self, ensure_ready: bool = True) -> None:
        self._task = None

    def terminate(self) -> None:
        pass

    def stop_call(self) -> None:
        pass

    def start_call(self) -> None:
        pass

    def compute_batch_size(self) -> int:
        return 1

    def batch_completed(self, batch_size, duration) -> None:
        pass

    def retrieval_context(self):
        import contextlib

        return contextlib.nullcontext()
