"""multiprocessing.Pool shim over cluster tasks.

Analog of ray: python/ray/util/multiprocessing/pool.py (Pool) — the same
drop-in `multiprocessing.Pool` surface (apply/apply_async/map/map_async/
imap/imap_unordered/starmap), each chunk of work running as a remote task
so a pool can span the whole cluster rather than one host's cores.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable

import ray_tpu

_CHUNK_TASK = None


def _chunk_task():
    global _CHUNK_TASK
    if _CHUNK_TASK is None:
        @ray_tpu.remote
        def _run_chunk(fn, chunk, star):
            if star:
                return [fn(*item) for item in chunk]
            return [fn(item) for item in chunk]
        _CHUNK_TASK = _run_chunk
    return _CHUNK_TASK


def _with_initializer(fn: Callable, initializer: Callable,
                      initargs: tuple, token: str) -> Callable:
    """Run `initializer` once per worker process before the first item
    (multiprocessing Pool(initializer=...) semantics; workers are pooled,
    so a process-global sentinel — one per Pool — dedups across chunks)."""
    def wrapper(*args):
        import builtins

        if not getattr(builtins, token, False):
            initializer(*initargs)
            setattr(builtins, token, True)
        return fn(*args)
    return wrapper


_init_ids = itertools.count()


class AsyncResult:
    """multiprocessing.pool.AsyncResult lookalike over ObjectRefs."""

    def __init__(self, refs: list, single: bool, callback=None,
                 error_callback=None):
        self._refs = refs
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._result = None
        self._done = False

    def get(self, timeout: float | None = None):
        if not self._done:
            try:
                chunks = ray_tpu.get(self._refs, timeout=timeout)
            except Exception as e:
                if self._error_callback:
                    self._error_callback(e)
                raise
            flat = [x for c in chunks for x in c]
            self._result = flat[0] if self._single else flat
            self._done = True
            if self._callback:
                self._callback(self._result)
        return self._result

    def wait(self, timeout: float | None = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs),
                     timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Drop-in multiprocessing.Pool running on the cluster
    (ray: util/multiprocessing/pool.py Pool)."""

    def __init__(self, processes: int | None = None,
                 initializer: Callable | None = None,
                 initargs: tuple = (), ray_address: str | None = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=ray_address)
        if processes is None:
            processes = max(
                1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        self._initializer = initializer
        self._initargs = initargs
        self._init_token = f"_ray_tpu_pool_init_{next(_init_ids)}"
        self._closed = False

    # -------------------------------------------------------------- helpers
    def _check(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: int | None,
                star: bool) -> list[list]:
        items = list(iterable)
        if chunksize is None:
            # same heuristic as multiprocessing: ~4 chunks per process
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], star

    def _submit(self, fn, chunks, star):
        task = _chunk_task()
        if self._initializer:
            fn = _with_initializer(fn, self._initializer, self._initargs,
                                   self._init_token)
        return [task.remote(fn, c, star) for c in chunks]

    # ------------------------------------------------------------------ api
    def apply(self, fn: Callable, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: tuple = (),
                    kwds: dict | None = None, callback=None,
                    error_callback=None) -> AsyncResult:
        self._check()
        kwds = kwds or {}

        @ray_tpu.remote
        def _apply(a, kw):
            return fn(*a, **kw)
        ref = _apply.remote(args, kwds)

        class _One(AsyncResult):
            def get(self, timeout=None):
                if not self._done:
                    try:
                        self._result = ray_tpu.get(self._refs[0],
                                                   timeout=timeout)
                    except Exception as e:
                        if self._error_callback:
                            self._error_callback(e)
                        raise
                    self._done = True
                    if self._callback:
                        self._callback(self._result)
                return self._result
        return _One([ref], True, callback, error_callback)

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: int | None = None) -> list:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: int | None = None, callback=None,
                  error_callback=None) -> AsyncResult:
        self._check()
        chunks, star = self._chunks(iterable, chunksize, False)
        return AsyncResult(self._submit(fn, chunks, star), False,
                           callback, error_callback)

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: int | None = None) -> list:
        self._check()
        chunks, star = self._chunks(iterable, chunksize, True)
        return AsyncResult(self._submit(fn, chunks, star), False).get()

    def starmap_async(self, fn: Callable, iterable: Iterable,
                      chunksize: int | None = None) -> AsyncResult:
        self._check()
        chunks, star = self._chunks(iterable, chunksize, True)
        return AsyncResult(self._submit(fn, chunks, star), False)

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: int = 1):
        self._check()
        chunks, star = self._chunks(iterable, chunksize, False)
        refs = self._submit(fn, chunks, star)
        for ref in refs:                     # ordered
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: int = 1):
        self._check()
        chunks, star = self._chunks(iterable, chunksize, False)
        refs = self._submit(fn, chunks, star)
        pending = list(refs)
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            for ref in done:
                yield from ray_tpu.get(ref)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self) -> "Pool":
        self._check()
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
