"""Custom serializer registration (ray: python/ray/util/serialization.py).

`register_serializer(cls, serializer=..., deserializer=...)` makes every
object-plane pickle of EXACTLY `cls` (subclasses excluded, as in the
reference) go through the given functions.  One-sided contract: the
deserializer is shipped by value inside the pickle stream, so receiving
workers never need to register anything.
"""
from __future__ import annotations

from typing import Any, Callable

from ray_tpu._private.serialization import _custom_serializers


def register_serializer(cls: type, *, serializer: Callable[[Any], Any],
                        deserializer: Callable[[Any], Any]) -> None:
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls: type) -> None:
    _custom_serializers.pop(cls, None)
