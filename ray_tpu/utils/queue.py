"""Distributed FIFO queue backed by an async actor
(analog of ray: python/ray/util/queue.py)."""
from __future__ import annotations

from typing import Any


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio

        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item: Any, timeout: float | None = None) -> bool:
        import asyncio

        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: float | None = None):
        import asyncio

        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item: Any) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except Exception:  # noqa: BLE001 - asyncio.QueueFull
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except Exception:  # noqa: BLE001 - asyncio.QueueEmpty
            return False, None

    async def put_nowait_batch(self, items: list) -> bool:
        # All-or-nothing (ray: put_nowait_batch raises Full if the whole
        # batch does not fit).
        if self._q.maxsize and \
                self._q.qsize() + len(items) > self._q.maxsize:
            return False
        for it in items:
            self._q.put_nowait(it)
        return True

    async def get_nowait_batch(self, n: int):
        if self._q.qsize() < n:
            return False, []
        return True, [self._q.get_nowait() for _ in range(n)]

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()

    async def full(self) -> bool:
        return self._q.full()


class Empty(Exception):
    pass


class Full(Exception):
    pass


class Queue:
    def __init__(self, maxsize: int = 0, name: str | None = None):
        import ray_tpu

        cls = ray_tpu.remote(_QueueActor)
        if name:
            cls = cls.options(name=name)
        self._actor = cls.remote(maxsize)

    def put(self, item: Any, timeout: float | None = None) -> None:
        import ray_tpu

        ok = ray_tpu.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full("queue put timed out")

    def get(self, timeout: float | None = None) -> Any:
        import ray_tpu

        ok, value = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty("queue get timed out")
        return value

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.full.remote())

    def size(self) -> int:
        return self.qsize()

    def put_nowait(self, item: Any) -> None:
        import ray_tpu

        if not ray_tpu.get(self._actor.put_nowait.remote(item)):
            raise Full("queue is full")

    def get_nowait(self) -> Any:
        import ray_tpu

        ok, value = ray_tpu.get(self._actor.get_nowait.remote())
        if not ok:
            raise Empty("queue is empty")
        return value

    def put_nowait_batch(self, items: list) -> None:
        import ray_tpu

        if not ray_tpu.get(self._actor.put_nowait_batch.remote(
                list(items))):
            raise Full("batch does not fit")

    def get_nowait_batch(self, n: int) -> list:
        import ray_tpu

        ok, items = ray_tpu.get(self._actor.get_nowait_batch.remote(n))
        if not ok:
            raise Empty(f"queue holds fewer than {n} items")
        return items

    def shutdown(self, force: bool = False) -> None:
        """Kill the backing actor (ray: Queue.shutdown); the queue is
        unusable afterwards."""
        import ray_tpu

        ray_tpu.kill(self._actor)

    def __reduce__(self):
        return (Queue._from_actor, (self._actor,))

    @classmethod
    def _from_actor(cls, actor) -> "Queue":
        q = cls.__new__(cls)
        q._actor = actor
        return q
