"""Distributed FIFO queue backed by an async actor
(analog of ray: python/ray/util/queue.py)."""
from __future__ import annotations

from typing import Any


class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio

        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item: Any, timeout: float | None = None) -> bool:
        import asyncio

        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: float | None = None):
        import asyncio

        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def empty(self) -> bool:
        return self._q.empty()


class Empty(Exception):
    pass


class Full(Exception):
    pass


class Queue:
    def __init__(self, maxsize: int = 0, name: str | None = None):
        import ray_tpu

        cls = ray_tpu.remote(_QueueActor)
        if name:
            cls = cls.options(name=name)
        self._actor = cls.remote(maxsize)

    def put(self, item: Any, timeout: float | None = None) -> None:
        import ray_tpu

        ok = ray_tpu.get(self._actor.put.remote(item, timeout))
        if not ok:
            raise Full("queue put timed out")

    def get(self, timeout: float | None = None) -> Any:
        import ray_tpu

        ok, value = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty("queue get timed out")
        return value

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self._actor.empty.remote())

    def __reduce__(self):
        return (Queue._from_actor, (self._actor,))

    @classmethod
    def _from_actor(cls, actor) -> "Queue":
        q = cls.__new__(cls)
        q._actor = actor
        return q
