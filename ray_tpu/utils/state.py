"""State API: programmatic cluster observability.

Analog of ray: python/ray/util/state/api.py (StateApiClient:110,
list_actors:781, summarize_tasks:1365) — list/get/summarize entities from
the controller (the GCS analog).
"""
from __future__ import annotations

import json
from typing import Any


def _core():
    from ray_tpu._private.worker import global_worker

    return global_worker()


def list_nodes() -> list[dict]:
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_nodes", timeout=30.0)
    return reply["nodes"]


def list_actors(filters: list[tuple] | None = None) -> list[dict]:
    """ray: util/state/api.py list_actors (filters like
    [("state", "=", "ALIVE")])."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_actors", timeout=30.0)
    actors = reply["actors"]
    for f in filters or ():
        key, op, val = f
        if op == "=":
            actors = [a for a in actors if a.get(key) == val]
        elif op == "!=":
            actors = [a for a in actors if a.get(key) != val]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return actors


def list_tasks(limit: int = 1000) -> list[dict]:
    """Task state-transition events (ray: list_tasks over
    GcsTaskManager's buffer)."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "get_task_events",
                         timeout=30.0)
    return reply["events"][-limit:]


def list_placement_groups() -> list[dict]:
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_pgs", timeout=30.0)
    return reply["pgs"]


def list_jobs() -> list[dict]:
    from ray_tpu.job_submission import JobSubmissionClient

    return JobSubmissionClient().list_jobs()


def _p(sorted_vals: list[float], q: float) -> float:
    from ray_tpu.utils.metrics import percentile

    return percentile(sorted_vals, q)


def summarize_tasks() -> dict:
    """Per-function task summary over the task-event buffer (ray:
    summarize_tasks api.py:1365): state counts plus duration p50/p95
    in ms (first SUBMITTED/RUNNING → FINISHED/FAILED per task), so
    "which function is slow" is answerable without a trace harvest."""
    # The buffer interleaves per-process push batches, so ORDER is not
    # time (a driver's SUBMITTED batch can land after the worker's
    # FINISHED) — sort by (t, lifecycle rank) first, the
    # utils/tracing.spans_from_events convention, so duration pairing
    # sees opens before closes and `latest` really is the last state.
    rank = {"SUBMITTED": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}
    events = sorted(list_tasks(limit=100_000),
                    key=lambda e: (e.get("t", 0.0),
                                   rank.get(e.get("state"), 0)))
    latest: dict[str, dict] = {}
    first_t: dict[str, float] = {}
    durations: dict[str, list[float]] = {}
    names: dict[str, str] = {}
    for ev in events:
        tid = ev["task_id"]
        latest[tid] = ev
        name = ev.get("name") or ev.get("function")
        if name:
            names[tid] = name
        t = ev.get("t", 0.0)
        if ev.get("state") in ("SUBMITTED", "RUNNING"):
            first_t.setdefault(tid, t)
        elif ev.get("state") in ("FINISHED", "FAILED") \
                and tid in first_t:
            # Pop at the terminal event: a retried task re-opens at its
            # next RUNNING, so each ATTEMPT measures its own duration —
            # never the original submit through every retry's backoff.
            durations.setdefault(tid, []).append(t - first_t.pop(tid))
    summary: dict[str, dict] = {}
    by_fn_durs: dict[str, list[float]] = {}
    for tid, ev in latest.items():
        fn = names.get(tid) or "?"
        state = ev.get("state", "?")
        row = summary.setdefault(fn, {"states": {}, "duration_ms": None})
        row["states"][state] = row["states"].get(state, 0) + 1
        for d in durations.get(tid, ()):
            by_fn_durs.setdefault(fn, []).append(d * 1000.0)
    for fn, durs in by_fn_durs.items():
        durs.sort()
        summary[fn]["duration_ms"] = {
            "p50": round(_p(durs, 0.50), 3),
            "p95": round(_p(durs, 0.95), 3),
            "count": len(durs),
        }
    return {"cluster": {"summary": summary,
                        "total_tasks": len(latest)}}


def summarize_actors() -> dict:
    summary: dict[str, int] = {}
    for a in list_actors():
        summary[a["state"]] = summary.get(a["state"], 0) + 1
    return {"cluster": {"summary_by_state": summary}}


def list_metrics() -> list[dict]:
    """Aggregated application metrics from every worker's last flush
    (ray: per-node Prometheus endpoints; see ray_tpu.utils.metrics).
    One kv_multiget round trip regardless of worker count (the old
    per-key kv_get loop paid one RT per worker)."""
    core = _core()
    reply, blobs = core.call(core.controller_addr, "kv_multiget",
                             {"ns": "metrics", "prefix": ""},
                             timeout=30.0)
    out = []
    for key, blob in zip(reply.get("keys", []), blobs):
        snap = json.loads(bytes(blob))
        snap["worker_id"] = key
        out.append(snap)
    return out


# ----------------------------------------------------- object ledger
def _apply_filters(rows: list[dict], filters) -> list[dict]:
    for f in filters or ():
        key, op, val = f
        if op == "=":
            rows = [r for r in rows if r.get(key) == val]
        elif op == "!=":
            rows = [r for r in rows if r.get(key) != val]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return rows


def _harvest_memory(limit: int,
                    timeout: float) -> tuple[list, list, list, list]:
    """Collect every process's `memory`-verb reply — this process's
    directly, the cluster's through the controller broadcast (the
    spans-harvest fan-out shape; the controller adds a fan-out leg to
    every RUNNING job driver — drivers own objects but no agent
    supervises them).  Returns (worker-ish replies, agent replies as
    (node_id, reply), diagnostics, driver diagnostics) deduped by boot
    token.  Agent/worker diagnostics make the harvest PARTIAL (claim
    sets are missing); driver diagnostics are reported separately — a
    dead driver's absence is itself a finding, not a hole."""
    from ray_tpu import memledger

    procs: list[dict] = []
    agents: list[tuple[str, dict]] = []
    diags: list[str] = []
    driver_diags: list[str] = []
    seen: set = set()

    def _take(reply) -> bool:
        if not isinstance(reply, dict) or "objects" not in reply:
            return False
        key = reply.get("boot") or reply.get("pid")
        if key in seen:
            return False
        seen.add(key)
        procs.append(reply)
        return True

    _take(memledger.collect(limit=limit))
    try:
        core = _core()
        reply, _ = core.call(core.controller_addr, "memory",
                             {"op": "collect", "broadcast": True,
                              "limit": limit}, timeout=timeout)
    except Exception as e:  # noqa: BLE001 - no cluster: local only
        diags.append(f"controller: {e!r}")
        reply = {}
    _take(reply)
    for node_id, nrep in (reply.get("nodes") or {}).items():
        if not isinstance(nrep, dict) or "objects" not in nrep:
            # A crashed/wedged agent (the memory.harvest failpoint
            # shape): the merged table stays partial WITH a diagnostic,
            # never a silent hole.
            err = nrep.get("error") if isinstance(nrep, dict) else nrep
            diags.append(f"node {node_id[:12]}: {err}")
            continue
        if _take(nrep):
            agents.append((node_id, nrep))
        for wid, wrep in (nrep.get("workers") or {}).items():
            if not isinstance(wrep, dict) or "objects" not in wrep:
                err = (wrep.get("error")
                       if isinstance(wrep, dict) else wrep)
                diags.append(f"worker {wid[:12]}: {err}")
                continue
            _take(wrep)
    for jid, drep in (reply.get("drivers") or {}).items():
        if not isinstance(drep, dict) or "objects" not in drep:
            err = drep.get("error") if isinstance(drep, dict) else drep
            if isinstance(drep, dict) and drep.get("gone"):
                # Confirmed-gone driver: its absence is a finding, not
                # a hole — the gauge stays computable.
                driver_diags.append(f"driver {jid[:12]}: {err}")
            else:
                # ALIVE driver that failed to answer (ping succeeded):
                # its claim set is missing, so the harvest is partial
                # exactly like a failed worker leg.
                diags.append(f"driver {jid[:12]}: {err}")
            continue
        _take(drep)
    return procs, agents, diags, driver_diags


def _merge_object_rows(procs: list, agents: list) -> tuple[list, dict]:
    """Join owner tables, borrower tables, arena pin attribution and
    spill state into one row per object (the `ray memory` table)."""
    rows: dict[str, dict] = {}
    truncated = 0
    for rep in procs:
        owner = rep.get("proc", "?")
        truncated += rep.get("truncated", 0)
        for o in rep.get("objects", ()):
            rows[o["object_id"]] = {
                "object_id": o["object_id"],
                "owner": owner, "owner_pid": rep.get("pid"),
                "owner_addr": rep.get("addr", ""),
                "node": (rep.get("node") or "")[:12],
                "size": o["size"], "state": o["state"],
                "tag": o["tag"], "callsite": o["callsite"],
                "age_s": o["age_s"],
                "local_refs": o["local_refs"],
                "borrowers": o["borrowers"],
                "contained": o["contained"],
                "locations": list(o.get("locations", ())),
                "tier": ("inline" if o["state"] == "inline"
                         else "arena" if o["state"] == "stored"
                         else o["state"]),
                "pins": 0, "pin_holders": [],
                "borrower_procs": [],
            }
    for node_id, rep in agents:
        store = rep.get("store") or {}
        truncated += store.get("truncated", 0)
        for e in store.get("objects", ()):
            row = rows.get(e["object_id"])
            if row is None:
                if not e["sealed"]:
                    # Creating-state block claimed by no owner: an
                    # in-flight pull/put assembly, not an object — the
                    # sentinel's dead-creator leg covers the crashed
                    # kind.
                    continue
                # Sealed in the arena but claimed by no harvested
                # owner: the unreachable-owner candidate the summarize
                # leg counts (gated there on creator liveness).
                row = rows[e["object_id"]] = {
                    "object_id": e["object_id"], "owner": None,
                    "owner_pid": None, "owner_addr": "", "node": "",
                    "size": e["size"], "state": "stored",
                    "tag": "unowned", "callsite": "?", "age_s": None,
                    "local_refs": 0, "borrowers": 0, "contained": 0,
                    "locations": [], "tier": "arena", "pins": 0,
                    "pin_holders": [], "borrower_procs": [],
                }
            row["tier"] = "arena"
            row["pins"] += e["pins"]
            if e["pins"] or e["pin_pids"]:
                row["pin_holders"].append(
                    {"node": node_id[:12], "pins": e["pins"],
                     "pids": e["pin_pids"]})
            row.setdefault("store_nodes", []).append(node_id[:12])
            row.setdefault("creator_pid", e["creator_pid"])
            # Any-host liveness suffices: replicas make creator pids
            # per-location, and one live creator means in-flight, not
            # leaked.
            row["creator_alive"] = (row.get("creator_alive", False)
                                    or e.get("creator_alive", False))
        for s in store.get("spilled", ()):
            row = rows.get(s["object_id"])
            if row is None:
                row = rows[s["object_id"]] = {
                    "object_id": s["object_id"], "owner": None,
                    "owner_pid": None, "owner_addr": "", "node": "",
                    "size": s.get("size", 0), "state": "stored",
                    "tag": "unowned",
                    "callsite": "?", "age_s": None, "local_refs": 0,
                    "borrowers": 0, "contained": 0, "locations": [],
                    "tier": "spill", "pins": 0, "pin_holders": [],
                    "borrower_procs": [],
                }
            row["tier"] = "spill"
            row.setdefault("store_nodes", []).append(node_id[:12])
    # Borrower attribution: which processes hold borrowed refs to each
    # object (the reference's borrower column).
    for rep in procs:
        for b in rep.get("borrows", ()):
            row = rows.get(b["object_id"])
            if row is not None:
                row["borrower_procs"].append(
                    {"proc": rep.get("proc", "?"),
                     "count": b["count"]})
    # Provider rows (HBM KV pools etc.) are their own entries.
    for rep in procs:
        for p in rep.get("provider_rows", ()):
            rows[f"{p.get('provider', '?')}:{p.get('object_id', '?')}"] = {
                "object_id": p.get("object_id", "?"),
                "owner": rep.get("proc", "?"),
                "owner_pid": rep.get("pid"), "owner_addr": "",
                "node": (rep.get("node") or "")[:12],
                "size": p.get("size", 0), "state": "resident",
                "tag": p.get("tag", "provider"),
                "callsite": p.get("callsite", p.get("provider", "?")),
                "age_s": None, "local_refs": 0, "borrowers": 0,
                "contained": 0, "locations": [],
                "tier": p.get("tier", "hbm"), "pins": 0,
                "pin_holders": [], "borrower_procs": [],
            }
    diag = {"truncated_rows": truncated}
    return list(rows.values()), diag


def list_objects(filters: list[tuple] | None = None,
                 limit: int = 5000, timeout: float = 30.0) -> list[dict]:
    """Cluster object table with ownership/pin attribution (ray:
    util/state/api.py list_objects + `ray memory` rows): one row per
    object — owner process, size, semantic tag, creation callsite,
    age, tier (inline / arena / spill / hbm), every store location,
    every pin holder (node + pid), every borrower.  Filters like
    [("tag", "=", "kv_export")] — `=`/`!=` over row keys.  `limit`
    bounds BOTH each per-process reply and the merged result (biggest
    rows survive, matching the per-reply truncation)."""
    procs, agents, _diags, _ddiags = _harvest_memory(limit, timeout)
    rows, _diag = _merge_object_rows(procs, agents)
    rows.sort(key=lambda r: -r["size"])
    return _apply_filters(rows, filters)[:limit]


def summarize_objects(limit: int = 5000, timeout: float = 30.0) -> dict:
    """Per-callsite grouped object summary (ray: `ray memory`'s
    --group-by=STACK_TRACE table / summarize_objects), plus the leak
    sentinel's cluster gauges: orphan pin bytes from every node's last
    scan and the unreachable-owner bytes computed by cross-referencing
    arena objects against every harvested owner table."""
    return _summarize_from(*_harvest_memory(limit, timeout))


def _summarize_from(procs: list, agents: list, diags: list,
                    driver_diags: list) -> dict:
    """summarize_objects over an already-collected harvest — one
    fan-out can feed both the row table and the summary (the CLI and
    dashboard would otherwise pay the cluster broadcast twice)."""
    rows, diag = _merge_object_rows(procs, agents)
    groups: dict[str, dict] = {}
    by_tag: dict[str, dict] = {}
    by_node: dict[str, dict] = {}
    total_bytes = 0
    for r in rows:
        total_bytes += r["size"]
        g = groups.setdefault(r["callsite"], {"count": 0, "bytes": 0,
                                              "tags": {}})
        g["count"] += 1
        g["bytes"] += r["size"]
        g["tags"][r["tag"]] = g["tags"].get(r["tag"], 0) + 1
        t = by_tag.setdefault(r["tag"], {"count": 0, "bytes": 0})
        t["count"] += 1
        t["bytes"] += r["size"]
        for node in r.get("store_nodes") or ([r["node"]]
                                             if r["node"] else []):
            n = by_node.setdefault(node, {"count": 0, "bytes": 0})
            n["count"] += 1
            n["bytes"] += r["size"]
    leaks: dict = {"arena_orphan_pin_bytes": 0, "arena_orphan_pins": 0,
                   "creating_dead_creator_bytes": 0}
    for _node_id, rep in agents:
        s = rep.get("sentinel") or {}
        leaks["arena_orphan_pin_bytes"] += s.get(
            "arena_orphan_pin_bytes", 0)
        leaks["arena_orphan_pins"] += s.get("arena_orphan_pins", 0)
        leaks["creating_dead_creator_bytes"] += s.get(
            "creating_dead_creator_bytes", 0)
    if diags or diag["truncated_rows"]:
        # A partial or truncated harvest cannot prove an owner absent:
        # report the gap instead of a false leak number.  (Driver
        # diagnostics don't nullify — a GONE driver's absence is the
        # finding; its sealed objects fail the creator-liveness gate
        # below and count.)
        leaks["objects_unreachable_owner_bytes"] = None
        leaks["unreachable_owner_objects"] = None
    else:
        # Sealed, claimed by NO harvested owner, and its creator pid is
        # dead on every host that holds it: the creator gate keeps a
        # concurrent in-flight put (sealed between a remote owner's
        # reply and this agent's scan) from reading as a leak.
        unreach = [r for r in rows
                   if r["owner"] is None
                   and not r.get("creator_alive", False)]
        leaks["objects_unreachable_owner_bytes"] = sum(
            r["size"] for r in unreach)
        leaks["unreachable_owner_objects"] = len(unreach)
    return {"cluster": {
        "summary": groups, "by_tag": by_tag, "by_node": by_node,
        "total_objects": len(rows), "total_bytes": total_bytes,
        "leaks": leaks,
        "partial": diags, "driver_diags": driver_diags, **diag,
    }}


def get_actor(actor_id: str) -> dict | None:
    for a in list_actors():
        if a["actor_id"] == actor_id:
            return a
    return None


def get_log(job_id: str | None = None, tail: int = 100) -> str:
    """Job driver logs (ray: get_log / ray logs)."""
    from ray_tpu.job_submission import JobSubmissionClient

    if job_id is None:
        raise ValueError("job_id required")
    logs = JobSubmissionClient().get_job_logs(job_id)
    return "\n".join(logs.splitlines()[-tail:])
