"""State API: programmatic cluster observability.

Analog of ray: python/ray/util/state/api.py (StateApiClient:110,
list_actors:781, summarize_tasks:1365) — list/get/summarize entities from
the controller (the GCS analog).
"""
from __future__ import annotations

import json
from typing import Any


def _core():
    from ray_tpu._private.worker import global_worker

    return global_worker()


def list_nodes() -> list[dict]:
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_nodes", timeout=30.0)
    return reply["nodes"]


def list_actors(filters: list[tuple] | None = None) -> list[dict]:
    """ray: util/state/api.py list_actors (filters like
    [("state", "=", "ALIVE")])."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_actors", timeout=30.0)
    actors = reply["actors"]
    for f in filters or ():
        key, op, val = f
        if op == "=":
            actors = [a for a in actors if a.get(key) == val]
        elif op == "!=":
            actors = [a for a in actors if a.get(key) != val]
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return actors


def list_tasks(limit: int = 1000) -> list[dict]:
    """Task state-transition events (ray: list_tasks over
    GcsTaskManager's buffer)."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "get_task_events",
                         timeout=30.0)
    return reply["events"][-limit:]


def list_placement_groups() -> list[dict]:
    core = _core()
    reply, _ = core.call(core.controller_addr, "list_pgs", timeout=30.0)
    return reply["pgs"]


def list_jobs() -> list[dict]:
    from ray_tpu.job_submission import JobSubmissionClient

    return JobSubmissionClient().list_jobs()


def summarize_tasks() -> dict:
    """Counts by (function, state) (ray: summarize_tasks api.py:1365)."""
    latest: dict[str, dict] = {}
    for ev in list_tasks(limit=100_000):
        latest[ev["task_id"]] = ev
    summary: dict[str, dict[str, int]] = {}
    for ev in latest.values():
        fn = ev.get("name") or ev.get("function", "?")
        state = ev.get("state", "?")
        summary.setdefault(fn, {})
        summary[fn][state] = summary[fn].get(state, 0) + 1
    return {"cluster": {"summary": summary,
                        "total_tasks": len(latest)}}


def summarize_actors() -> dict:
    summary: dict[str, int] = {}
    for a in list_actors():
        summary[a["state"]] = summary.get(a["state"], 0) + 1
    return {"cluster": {"summary_by_state": summary}}


def list_metrics() -> list[dict]:
    """Aggregated application metrics from every worker's last flush
    (ray: per-node Prometheus endpoints; see ray_tpu.utils.metrics)."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "kv_keys",
                         {"ns": "metrics"}, timeout=30.0)
    out = []
    for key in reply.get("keys", []):
        r, blobs = core.call(core.controller_addr, "kv_get",
                             {"ns": "metrics", "key": key}, timeout=30.0)
        if blobs:
            snap = json.loads(bytes(blobs[0]))
            snap["worker_id"] = key
            out.append(snap)
    return out


def get_actor(actor_id: str) -> dict | None:
    for a in list_actors():
        if a["actor_id"] == actor_id:
            return a
    return None


def get_log(job_id: str | None = None, tail: int = 100) -> str:
    """Job driver logs (ray: get_log / ray logs)."""
    from ray_tpu.job_submission import JobSubmissionClient

    if job_id is None:
        raise ValueError("job_id required")
    logs = JobSubmissionClient().get_job_logs(job_id)
    return "\n".join(logs.splitlines()[-tail:])
