"""Serialization debugging: find WHICH captured object cannot pickle.

Analog of ray: python/ray/util/check_serialize.py
(inspect_serializability: recursively probes a function's closure /
globals / an object's attributes with cloudpickle and reports the
deepest failing members).  Re-designed around a plain recursive probe
that returns structured findings (the reference prints a colorama tree;
here the report is data first, text second — callers and tests consume
the tuples, __str__ renders the tree)."""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any

import cloudpickle


@dataclass(eq=False)   # identity hash: instances go in the result set
class FailureTuple:
    """One non-serializable member: the object, the name it was reached
    by, and the object that references it."""

    obj: Any
    name: str
    parent: Any

    def __repr__(self):
        return f"FailTuple({self.name} [obj={self.obj!r}, " \
               f"parent={self.parent!r}])"


@dataclass
class SerializationReport:
    serializable: bool
    failures: list = field(default_factory=list)
    trace: list = field(default_factory=list)

    def __str__(self):
        lines = list(self.trace)
        if self.failures:
            lines.append("non-serializable members:")
            lines += [f"  {f!r}" for f in self.failures]
        return "\n".join(lines)


def _try_pickle(obj: Any) -> Exception | None:
    try:
        cloudpickle.dumps(obj)
        return None
    except Exception as e:  # noqa: BLE001 - the probe exists to catch all
        return e


def _probe_members(obj: Any, name: str, report: SerializationReport,
                   depth: int, seen: set) -> None:
    """Recurse into the members cloudpickle would serialize, recording
    the DEEPEST failing ones (a failing leaf explains its parents)."""
    if depth <= 0 or id(obj) in seen:
        report.failures.append(FailureTuple(obj, name, None))
        return
    seen.add(id(obj))

    members: list[tuple[str, Any, Any]] = []   # (name, member, parent)
    if inspect.isfunction(obj):
        try:
            closure = inspect.getclosurevars(obj)
        except (TypeError, ValueError):
            closure = None
        if closure is not None:
            members += [(f"{name}.<global {k}>", v, obj)
                        for k, v in closure.globals.items()]
            members += [(f"{name}.<closure {k}>", v, obj)
                        for k, v in closure.nonlocals.items()]
        # Default argument values ride the pickle too (cloudpickle
        # serializes __defaults__/__kwdefaults__ by value).
        try:
            params = inspect.signature(obj).parameters
            members += [(f"{name}.<default {k}>", p.default, obj)
                        for k, p in params.items()
                        if p.default is not inspect.Parameter.empty]
        except (TypeError, ValueError):
            pass
    elif inspect.isclass(obj):
        # The class's OWN dict (a mappingproxy): methods and class
        # attributes — the primary actor-class diagnosis case.
        members += [(f"{name}.{k}", v, obj)
                    for k, v in vars(obj).items()
                    if not k.startswith("__")]
    else:
        state = getattr(obj, "__dict__", None)
        if hasattr(state, "items"):
            members += [(f"{name}.{k}", v, obj) for k, v in state.items()]

    found_deeper = False
    for mname, member, parent in members:
        err = _try_pickle(member)
        if err is None:
            continue
        report.trace.append(f"{mname}: {type(err).__name__}: {err}")
        sub = SerializationReport(False)
        _probe_members(member, mname, sub, depth - 1, seen)
        if sub.failures:
            report.failures += sub.failures
            report.trace += sub.trace
        else:
            report.failures.append(FailureTuple(member, mname, parent))
        found_deeper = True
    if not found_deeper:
        # The object itself is the leaf failure.
        report.failures.append(FailureTuple(obj, name, None))


def inspect_serializability(obj: Any, name: str | None = None,
                            depth: int = 3, print_file=None,
                            ) -> tuple[bool, set]:
    """Probe `obj` for cloudpickle serializability.

    Returns (serializable, set_of_FailureTuple) like the reference
    (`ray.util.inspect_serializability`); prints the findings to
    `print_file` (default stdout) when not serializable.
    """
    name = name or getattr(obj, "__qualname__", None) or repr(obj)
    err = _try_pickle(obj)
    if err is None:
        return True, set()
    report = SerializationReport(False)
    report.trace.append(f"{name}: {type(err).__name__}: {err}")
    _probe_members(obj, name, report, depth, set())
    # De-dup by (name, id(obj)): the same leaf can be reached through
    # several parents.  The printed tree renders the SAME deduped set
    # the caller gets.
    uniq: dict[tuple, FailureTuple] = {}
    for f in report.failures:
        uniq[(f.name, id(f.obj))] = f
    report.failures = list(uniq.values())
    report.trace = list(dict.fromkeys(report.trace))
    print(str(report), file=print_file)
    return False, set(report.failures)
