"""ray_tpu-on-Spark: launch a ray_tpu cluster on a Spark cluster.

Analog of ray: python/ray/util/spark/cluster_init.py
(setup_ray_cluster:895, RayClusterOnSpark, _setup_ray_cluster:462) +
start_ray_node.py (the per-executor node babysitter).  The head
(controller + head node agent) starts on the Spark driver host; each
worker node is one long-running barrier-stage task on an executor that
babysits a node agent until the Spark job is cancelled.

The Spark surface is a small injected interface (SparkJobRunner), so the
orchestration — head startup, per-executor agent launch, readiness wait,
cancellation teardown — is real, tested code without pyspark in the
image; when pyspark IS importable, PySparkJobRunner submits the genuine
background barrier job (reference: cluster_init.py `_start_ray_worker_nodes`
job-group pattern).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Callable

_active_cluster: "RayTpuClusterOnSpark | None" = None


def _call_controller(addr: str, method: str, header: dict | None = None,
                     timeout: float = 15.0):
    """One-shot controller RPC without joining the cluster as a driver."""
    import asyncio

    async def _go():

        from ray_tpu._private.rpc import RpcClient

        cli = RpcClient(address=addr)
        try:
            reply, _ = await cli.call(method, header or {},
                                      timeout=timeout)
            return reply
        finally:
            cli.close()

    return asyncio.run(_go())


def _worker_node_main(head_addr: str, resources: dict | None,
                      check_cancelled: Callable[[], bool]) -> None:
    """Per-executor body (reference: start_ray_node.py — spawn the node
    process, then babysit until the Spark task is cancelled/killed)."""
    from ray_tpu.api import _read_json_line

    args = [sys.executable, "-m", "ray_tpu._private.node_agent",
            "--controller", head_addr]
    if resources:
        args += ["--resources-json", json.dumps(resources)]
    # Three layered kill paths for the agent (a cancelled Spark task can
    # die by SIGKILL before the finally below runs, and the agent lives
    # in its own session): (1) this babysitter's finally, (2) the agent's
    # parent-watch (exits if the Spark python worker dies), (3) suicide
    # when the head stays unreachable after cluster shutdown.
    env = {**os.environ, "RAY_TPU_EXIT_ON_HEAD_LOSS": "60"}
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            start_new_session=True, env=env)
    _read_json_line(proc)
    try:
        while not check_cancelled() and proc.poll() is None:
            time.sleep(0.5)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


class SparkJobRunner:
    """How worker-node tasks reach executors.  `run_on_executors` starts
    fn(partition_index, check_cancelled) on n executors WITHOUT blocking;
    `cancel` stops them all (the agents' babysitters see it and exit)."""

    def run_on_executors(self, fn: Callable, n: int):
        raise NotImplementedError

    def cancel(self, handle) -> None:
        raise NotImplementedError


class PySparkJobRunner(SparkJobRunner):
    """Real Spark backend: one background barrier-stage job in its own
    job group (reference: cluster_init.py spark job-group + barrier mode
    so all worker nodes schedule together or not at all)."""

    def __init__(self, spark=None):
        if spark is None:
            from pyspark.sql import SparkSession

            spark = SparkSession.getActiveSession()
        if spark is None:
            raise RuntimeError("no active SparkSession; pass spark=")
        self.spark = spark

    def run_on_executors(self, fn: Callable, n: int):
        sc = self.spark.sparkContext
        group = f"raytpu-cluster-{os.getpid()}-{time.time():.0f}"

        def _partition(it):
            from pyspark import BarrierTaskContext

            ctx = BarrierTaskContext.get()
            idx = next(iter(it))
            # Spark cancellation kills the task thread; the babysitter's
            # finally-terminate runs via the interruption exception.
            fn(idx, lambda: False)
            yield 0

        def _job():
            sc.setJobGroup(group, "ray_tpu worker nodes",
                           interruptOnCancel=True)
            try:
                sc.parallelize(range(n), n).barrier() \
                    .mapPartitions(_partition).collect()
            except Exception:  # noqa: BLE001 - cancelled at shutdown
                pass

        thread = threading.Thread(target=_job, daemon=True,
                                  name="raytpu-on-spark")
        thread.start()
        return (group, thread)

    def cancel(self, handle) -> None:
        group, thread = handle
        self.spark.sparkContext.cancelJobGroup(group)
        thread.join(timeout=30)


class LocalProcessJobRunner(SparkJobRunner):
    """Executor stand-in: each "executor" is a local thread driving the
    same per-node body.  This is what the shim's tests use (the reference
    tests against a local-mode Spark; the image has no pyspark)."""

    def __init__(self):
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def run_on_executors(self, fn: Callable, n: int):
        for i in range(n):
            t = threading.Thread(target=fn,
                                 args=(i, self._stop.is_set),
                                 daemon=True, name=f"raytpu-exec-{i}")
            t.start()
            self._threads.append(t)
        return self._threads

    def cancel(self, handle) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)


class RayTpuClusterOnSpark:
    """Handle to a running cluster (reference: RayClusterOnSpark —
    connect/disconnect/shutdown + context manager)."""

    def __init__(self, address: str, head_procs: list, runner: SparkJobRunner,
                 job_handle, num_worker_nodes: int):
        self.address = address
        self._head_procs = head_procs
        self._runner = runner
        self._job_handle = job_handle
        self.num_worker_nodes = num_worker_nodes
        self._shut = False

    def wait_until_ready(self, timeout: float = 120.0) -> None:
        """Block until every worker node registered with the head."""
        want = self.num_worker_nodes + 1   # + the head node
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                nodes = _call_controller(self.address, "list_nodes")["nodes"]
                if sum(1 for nd in nodes
                       if nd.get("state") == "ALIVE") >= want:
                    return
            except Exception:  # noqa: BLE001 - head still starting
                pass
            time.sleep(0.5)
        raise TimeoutError(
            f"spark worker nodes did not all join within {timeout}s")

    def connect(self):
        import ray_tpu

        ray_tpu.init(address=self.address)
        return ray_tpu

    def disconnect(self) -> None:
        import ray_tpu

        if ray_tpu.is_initialized():
            ray_tpu.shutdown()

    def shutdown(self) -> None:
        global _active_cluster
        if self._shut:
            return
        self._shut = True
        self.disconnect()
        try:
            self._runner.cancel(self._job_handle)
        except Exception:  # noqa: BLE001 - teardown
            pass
        for p in self._head_procs:
            p.terminate()
        for p in self._head_procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if _active_cluster is self:
            _active_cluster = None

    def __enter__(self):
        self.connect()
        return self

    def __exit__(self, *exc):
        self.shutdown()


def setup_ray_tpu_cluster(*, max_worker_nodes: int,
                          num_cpus_worker_node: int | None = None,
                          num_cpus_head_node: int = 0,
                          resources_worker_node: dict | None = None,
                          spark=None,
                          job_runner: SparkJobRunner | None = None,
                          timeout: float = 120.0):
    """Start a ray_tpu cluster across Spark executors; returns
    (address, cluster).  Reference: setup_ray_cluster (cluster_init.py:895)
    returns (address, remote_connection_address)."""
    global _active_cluster
    if _active_cluster is not None:
        raise RuntimeError("a ray_tpu-on-spark cluster is already active; "
                           "call shutdown_ray_tpu_cluster() first")
    from ray_tpu._private.config import Config
    from ray_tpu.api import _read_json_line

    config = Config()
    denv = {**os.environ, "RAY_TPU_DAEMONIZE": "1"}
    head_procs = []
    controller = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.controller",
         "--config-json", config.to_json()],
        stdout=subprocess.PIPE, start_new_session=True, env=denv)
    head_procs.append(controller)
    address = _read_json_line(controller)["controller_addr"]
    # Head-node agent: CPU=0 by default so user tasks land on the worker
    # nodes (reference: num_cpus_head_node defaults keep the driver light).
    head_agent = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--controller", address,
         "--resources-json", json.dumps({"CPU": num_cpus_head_node}),
         "--config-json", config.to_json()],
        stdout=subprocess.PIPE, start_new_session=True, env=denv)
    head_procs.append(head_agent)
    _read_json_line(head_agent)

    resources = dict(resources_worker_node or {})
    if num_cpus_worker_node is not None:
        resources.setdefault("CPU", num_cpus_worker_node)

    if job_runner is None:
        job_runner = PySparkJobRunner(spark)

    def _node(idx: int, check_cancelled: Callable[[], bool]) -> None:
        _worker_node_main(address, resources or None, check_cancelled)

    handle = job_runner.run_on_executors(_node, max_worker_nodes)
    cluster = RayTpuClusterOnSpark(address, head_procs, job_runner, handle,
                                   max_worker_nodes)
    try:
        cluster.wait_until_ready(timeout=timeout)
    except Exception:
        cluster.shutdown()
        raise
    _active_cluster = cluster
    return address, cluster


def shutdown_ray_tpu_cluster() -> None:
    """Reference: shutdown_ray_cluster (cluster_init.py)."""
    if _active_cluster is not None:
        _active_cluster.shutdown()
