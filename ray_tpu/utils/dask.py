"""Dask-on-ray_tpu scheduler: execute dask graphs as cluster tasks.

Analog of ray: python/ray/util/dask/scheduler.py (ray_dask_get:41 —
a dask scheduler that submits one Ray task per graph key and lets refs
flow as task arguments).  The dask graph format is plain data
({key: task_tuple_or_literal}), so the scheduler works — and is tested —
without dask installed; `enable_dask_on_ray_tpu()` additionally registers
it as dask's default scheduler when dask IS importable.

Semantics mirrored from the reference: one task per key, upstream
results travel as ObjectRefs (never through the driver), nested task
tuples execute inside the worker, `get(dsk, keys)` accepts dask's
(possibly nested) key lists.
"""
from __future__ import annotations

from typing import Any, Hashable

import ray_tpu


def _ishashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _is_task(expr) -> bool:
    """dask task convention: a tuple whose head is callable."""
    return isinstance(expr, tuple) and bool(expr) and callable(expr[0])


def _find_deps(expr, dsk, out: set) -> None:
    """Collect graph keys referenced by a task expression."""
    if _is_task(expr):
        for a in expr[1:]:
            _find_deps(a, dsk, out)
    elif isinstance(expr, list):
        for a in expr:
            _find_deps(a, dsk, out)
    elif _ishashable(expr) and expr in dsk:
        out.add(expr)


def _rebuild(expr, deps: dict):
    """Worker-side evaluation of one task expression: keys substitute
    their upstream values, nested task tuples execute depth-first."""
    if _is_task(expr):
        fn = expr[0]
        return fn(*[_rebuild(a, deps) for a in expr[1:]])
    if isinstance(expr, list):
        return [_rebuild(a, deps) for a in expr]
    if _ishashable(expr) and expr in deps:
        return deps[expr]
    return expr


@ray_tpu.remote
def _dask_task(expr, dep_keys, *dep_vals):
    return _rebuild(expr, dict(zip(dep_keys, dep_vals)))


def get(dsk: dict, keys, **_kwargs) -> Any:
    """The dask scheduler entry point (ray: ray_dask_get).

    Submits one ray_tpu task per graph key reachable from `keys`
    (dependency refs passed as task args, so the cluster pipelines the
    graph), then materializes the requested keys.
    """
    refs: dict[Hashable, Any] = {}

    def submit(key) -> Any:
        if key in refs:
            return refs[key]
        expr = dsk[key]
        deps: set = set()
        _find_deps(expr, dsk, deps)
        dep_keys = sorted(deps, key=str)
        dep_refs = [submit(k) for k in dep_keys]
        refs[key] = _dask_task.remote(expr, dep_keys, *dep_refs)
        return refs[key]

    def walk(k):
        if isinstance(k, list):
            return [walk(x) for x in k]
        return submit(k)

    ref_tree = walk(keys)

    def materialize(t):
        if isinstance(t, list):
            return [materialize(x) for x in t]
        return ray_tpu.get(t)

    return materialize(ref_tree)


def enable_dask_on_ray_tpu() -> None:
    """Make this scheduler dask's default (ray: enable_dask_on_ray).
    Requires dask; the raw `get` works without it."""
    import dask

    dask.config.set(scheduler=get)


def disable_dask_on_ray_tpu() -> None:
    import dask

    dask.config.set(scheduler=None)
