"""Placement groups: gang resource reservation across the cluster.

Analog of ray: python/ray/util/placement_group.py:41,145.  On TPU the
bundle is the unit of slice-coherent placement: STRICT_PACK puts every
bundle on one host (one ICI domain), STRICT_SPREAD gives per-host fault
isolation for multi-host training (SURVEY §2.4 gang-scheduling row).
"""
from __future__ import annotations

import time
from typing import Sequence

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: list[dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        # Set when create_pg reported CREATED inline (the controller
        # waits for the first reservation pass): ready() then needs no
        # RPC at all.  Deserialized handles re-ask the controller.
        self._created = False

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def ready(self, timeout: float = 60.0) -> bool:
        """Block until all bundles are reserved (ray: pg.ready())."""
        from ray_tpu import client as client_mod
        from ray_tpu._private.worker import global_worker

        if self._created:
            return True
        if client_mod._ctx is not None:
            return client_mod._ctx.pg_ready(self.id, timeout)
        core = global_worker()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            reply, _ = core.call(
                core.controller_addr, "pg_ready",
                {"pg_id": self.id, "wait": True,
                 "timeout": max(0.1, deadline - time.monotonic())},
                timeout=timeout + 10)
            if reply.get("state") == "CREATED":
                self._created = True
                return True
            if reply.get("state") == "REMOVED":
                return False
        return False

    def bundle_locations(self) -> dict[int, str]:
        from ray_tpu import client as client_mod
        from ray_tpu._private.worker import global_worker

        if client_mod._ctx is not None:
            return client_mod._ctx.pg_locations(self.id)
        core = global_worker()
        reply, _ = core.call(core.controller_addr, "pg_ready",
                             {"pg_id": self.id}, timeout=30.0)
        return {int(k): v for k, v in reply.get("bundle_nodes", {}).items()}

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: Sequence[dict[str, float]],
                    strategy: str = "PACK",
                    name: str | None = None,
                    lifetime: str | None = None) -> PlacementGroup:
    """lifetime=None ties the PG to this driver — the controller reaps
    its reservations if the driver dies without removing it (ray:
    job-scoped PG lifetime); lifetime="detached" opts out."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"invalid strategy {strategy!r}; valid: {VALID_STRATEGIES}")
    if lifetime not in (None, "detached"):
        raise ValueError(f"invalid lifetime {lifetime!r}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b!r}")
    from ray_tpu import client as client_mod
    from ray_tpu._private.ids import PlacementGroupID
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        pg_id = client_mod._ctx.pg_create(bundles, strategy, name,
                                          lifetime)
        return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)
    core = global_worker()
    pg_id = PlacementGroupID.from_random().hex()
    reply, _ = core.call(
        core.controller_addr, "create_pg",
        {"pg_id": pg_id, "bundles": [dict(b) for b in bundles],
         "strategy": strategy, "name": name, "wait": True,
         # Owner = the JOB's driver, not this process: a PG created
         # inside a task/actor must survive its worker being pooled,
         # recycled, or OOM-killed while the job lives (ray ties PG
         # lifetime to the job; the controller's owner reaper probes
         # this address).
         "owner": core.driver_addr,
         "detached": lifetime == "detached"}, timeout=30.0)
    pg = PlacementGroup(pg_id, [dict(b) for b in bundles], strategy)
    pg._created = reply.get("state") == "CREATED"
    return pg


def remove_placement_group(pg: PlacementGroup) -> None:
    """Posted, not awaited (ray: remove_placement_group returns once the
    GCS accepts the removal; actual bundle teardown is asynchronous
    there too).  Per-connection ordering still puts the removal before
    any later controller call from this process."""
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        client_mod._ctx.pg_remove(pg.id)
        return
    core = global_worker()
    pg._created = False
    core.call_nowait(core.controller_addr, "remove_pg", {"pg_id": pg.id})


def release_bundles(pg: PlacementGroup, bundle_indexes: list[int]) -> list:
    """Eagerly release specific bundles of a live PG (elastic train
    shrink: a dead worker's reservation must not block the autoscaler /
    regrow path until trial end).  Returns the indexes actually
    released; bundles already gone (dead node) are skipped."""
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        raise NotImplementedError(
            "per-bundle PG patching is not proxied in client mode")
    core = global_worker()
    reply, _ = core.call(core.controller_addr, "pg_release_bundles",
                         {"pg_id": pg.id,
                          "bundle_indexes": list(bundle_indexes)},
                         timeout=30.0)
    return reply.get("released", [])


def reschedule_placement_group(pg: PlacementGroup) -> str:
    """Ask the controller to re-reserve a PG's missing bundles (elastic
    regrow); returns the group's state after kicking the scheduler
    (PENDING until the holes fill, then CREATED via pg_ready)."""
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        raise NotImplementedError(
            "per-bundle PG patching is not proxied in client mode")
    core = global_worker()
    pg._created = False          # ready() must re-ask the controller
    reply, _ = core.call(core.controller_addr, "pg_reschedule",
                         {"pg_id": pg.id}, timeout=30.0)
    return reply.get("state", "UNKNOWN")


def placement_group_state(pg: PlacementGroup) -> str:
    """Non-blocking state probe (the regrow poll): CREATED / PENDING /
    REMOVED / UNKNOWN, without pg.ready()'s wait-for-created block."""
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        # The proxy only exposes a ready/not-ready bool, which cannot
        # distinguish PENDING from REMOVED — refuse rather than lie
        # (elastic runs, the only caller, are driver-side anyway).
        raise NotImplementedError(
            "per-bundle PG state probing is not proxied in client mode")
    core = global_worker()
    reply, _ = core.call(core.controller_addr, "pg_ready",
                         {"pg_id": pg.id}, timeout=30.0)
    return reply.get("state", "UNKNOWN")


def get_current_placement_group() -> "PlacementGroup | None":
    """The placement group the calling task/actor runs in, or None (ray:
    util/placement_group.py get_current_placement_group).  Tasks resolve
    through the executing worker's current bundle; actor methods through
    their hosting ActorInstance (each sync actor owns a dedicated
    executor, so the thread identifies the actor)."""
    import threading

    from ray_tpu._private.worker import _global_worker

    core = _global_worker
    if core is None:
        return None
    key = core.current_bundle_key
    if key is None:
        tname = threading.current_thread().name
        if tname.startswith("actor-"):
            prefix = tname[len("actor-"):].split("_")[0]
            for inst in core.actors_hosted.values():
                if inst.actor_id.startswith(prefix):
                    key = inst.bundle_key
                    break
        elif len(core.actors_hosted) == 1:
            # Async-actor methods run on the worker loop, not a named
            # executor thread; unambiguous only with one hosted actor.
            key = next(iter(core.actors_hosted.values())).bundle_key
    if not key:
        return None
    pg_id = key.rsplit(":", 1)[0]
    return _pg_from_table(pg_id)


def get_placement_group(name: str) -> "PlacementGroup":
    """Look up a placement group by name (ray:
    util/placement_group.py:175 get_placement_group)."""
    for row in placement_group_table():
        if row.get("name") == name:
            return PlacementGroup(row["pg_id"], row["bundles"],
                                  row["strategy"])
    raise ValueError(f"placement group {name!r} not found")


def _pg_from_table(pg_id: str) -> "PlacementGroup | None":
    for row in placement_group_table():
        if row["pg_id"] == pg_id:
            return PlacementGroup(pg_id, row["bundles"], row["strategy"])
    return None


def placement_group_table() -> list[dict]:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        return client_mod._ctx.pg_table()
    core = global_worker()
    reply, _ = core.call(core.controller_addr, "list_pgs", timeout=30.0)
    return reply["pgs"]
