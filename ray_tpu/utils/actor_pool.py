"""ActorPool: load-balance tasks over a fixed set of actors
(analog of ray: python/ray/util/actor_pool.py)."""
from __future__ import annotations

from typing import Any, Callable, Iterable


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._pending: list[tuple[Callable, Any]] = []
        self._results_order: list = []

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef"""
        if self._idle:
            actor = self._idle.pop(0)
            ref = fn(actor, value)
            self._future_to_actor[ref] = (actor, fn)
            self._results_order.append(ref)
        else:
            self._pending.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending)

    def has_free(self) -> bool:
        """True when an idle actor is available (ray:
        ActorPool.has_free)."""
        return bool(self._idle) and not self._pending

    def pop_idle(self):
        """Remove and return an idle actor, or None (ray: pop_idle)."""
        if self.has_free():
            return self._idle.pop(0)
        return None

    def push(self, actor) -> None:
        """Return an actor to the pool (ray: push); drains any queued
        submission onto it immediately."""
        self._idle.append(actor)
        if self._pending and self._idle:
            fn, value = self._pending.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        import ray_tpu

        if not self._results_order:
            raise StopIteration("no pending results")
        ref = self._results_order[0]
        try:
            value = ray_tpu.get(ref, timeout=timeout)
        except ray_tpu.GetTimeoutError:
            raise            # ref stays queued; a retry re-fetches this slot
        except Exception:
            # Task failed: recycle the actor, drop the slot, re-raise.
            self._results_order.pop(0)
            self._on_done(ref)
            raise
        self._results_order.pop(0)
        self._on_done(ref)
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        import ray_tpu

        if not self._future_to_actor:
            raise StopIteration("no pending results")
        done, _ = ray_tpu.wait(list(self._future_to_actor),
                               num_returns=1, timeout=timeout)
        if not done:
            raise TimeoutError("get_next_unordered timed out")
        ref = done[0]
        self._results_order.remove(ref)
        try:
            value = ray_tpu.get(ref)
        finally:
            self._on_done(ref)   # recycle the actor even when the task raised
        return value

    def _on_done(self, ref) -> None:
        actor, _fn = self._future_to_actor.pop(ref)
        if self._pending:
            fn, value = self._pending.pop(0)
            new_ref = fn(actor, value)
            self._future_to_actor[new_ref] = (actor, fn)
            self._results_order.append(new_ref)
        else:
            self._idle.append(actor)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()
