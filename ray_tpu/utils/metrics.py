"""Application metrics API: Counter / Gauge / Histogram.

Analog of ray: python/ray/util/metrics.py (Counter/Gauge/Histogram over the
C++ OpenCensus registry, src/ray/stats/metric_defs.cc).  Metrics are
buffered per process and flushed to the controller KV periodically; the
state API / dashboard reads the aggregated snapshot (the per-node
Prometheus-agent export of the reference, python/ray/_private/
metrics_agent.py, collapses to the controller here).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Sequence

_registry_lock = threading.Lock()
_registry: dict[str, "Metric"] = {}
_flusher: threading.Thread | None = None
FLUSH_PERIOD_S = 2.0


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] | None = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict[str, str] = {}
        # (tag tuple) -> value
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry[name] = self
        _ensure_flusher()

    def set_default_tags(self, tags: dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        unknown = set(merged) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys {unknown}; declared "
                             f"{self.tag_keys}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def remove(self, tags: dict | None = None) -> None:
        """Drop one tagged series from this metric.  Short-lived tag
        values (a per-replica tag under an autoscaler that cycles
        replicas all day) MUST be removed at teardown or the registry —
        and every snapshot riding it: telemetry ring samples, harvest
        replies, /metrics scrapes — grows without bound."""
        k = self._key(tags)
        with self._lock:
            self._values.pop(k, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name, "description": self.description,
                "type": type(self).__name__.lower(),
                "tag_keys": list(self.tag_keys),
                "values": [
                    {"tags": dict(zip(self.tag_keys, k)), "value": v}
                    for k, v in self._values.items()],
            }


class Counter(Metric):
    """Monotonic counter (ray: util/metrics.py Counter)."""

    def inc(self, value: float = 1.0, tags: dict | None = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + value


class Gauge(Metric):
    """Last-value gauge (ray: util/metrics.py Gauge)."""

    def set(self, value: float, tags: dict | None = None) -> None:
        with self._lock:
            self._values[self._key(tags)] = float(value)


class Histogram(Metric):
    """Bucketed histogram (ray: util/metrics.py Histogram)."""

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] | None = None,
                 tag_keys: Sequence[str] | None = None):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1.0, 10.0, 100.0])
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, tags: dict | None = None) -> None:
        k = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                k, [0] * (len(self.boundaries) + 1))
            i = 0
            while i < len(self.boundaries) and value > self.boundaries[i]:
                i += 1
            counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._values[k] = self._sums[k]   # snapshot shows the sum

    def remove(self, tags: dict | None = None) -> None:
        k = self._key(tags)
        with self._lock:
            self._values.pop(k, None)
            self._counts.pop(k, None)
            self._sums.pop(k, None)

    def snapshot(self) -> dict:
        base = super().snapshot()
        with self._lock:
            base["boundaries"] = self.boundaries
            base["counts"] = [
                {"tags": dict(zip(self.tag_keys, k)), "counts": c}
                for k, c in self._counts.items()]
        return base


def get_or_create(cls, name: str, description: str = "",
                  tag_keys: Sequence[str] | None = None, **kwargs):
    """Idempotent metric handle: return the registered metric when one
    of the same name and type exists, else create it.  Library code
    that may instantiate many times per process (e.g. one serve LLM
    engine per replica, many per test run) must use this instead of the
    constructor — re-constructing replaces the registry entry and
    silently drops the accumulated series."""
    with _registry_lock:
        m = _registry.get(name)
    if m is None:
        # The constructor registers itself (under the lock); two racing
        # creators both construct, the registry keeps the last writer —
        # re-read and return THAT one so every caller holds the same
        # handle and no series is silently dropped.
        cls(name, description, tag_keys=tag_keys, **kwargs)
        with _registry_lock:
            m = _registry[name]
    if type(m) is not cls:
        raise TypeError(
            f"metric {name!r} already registered as "
            f"{type(m).__name__}, requested {cls.__name__}")
    return m


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile over an ALREADY-SORTED sequence (0.0
    for empty) — the one summary-stat helper shared by the trace
    attribution and task-summary surfaces."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def registry_snapshots() -> list[dict]:
    """Snapshot every registered metric under the registry lock — the
    flush loop's walk, shared with the telemetry timeline sampler
    (_private/telemetry.py sample_now)."""
    with _registry_lock:
        return [m.snapshot() for m in _registry.values()]


def _ensure_flusher() -> None:
    """Push local metric snapshots to the controller KV (the metrics-agent
    export path, collapsed)."""
    global _flusher
    with _registry_lock:
        if _flusher is not None:
            return
        _flusher = threading.Thread(target=_flush_loop, daemon=True,
                                    name="metrics-flush")
        _flusher.start()


def _flush_loop() -> None:
    import json

    while True:
        time.sleep(FLUSH_PERIOD_S)
        try:
            from ray_tpu._private import telemetry
            from ray_tpu._private.worker import _global_worker

            core = _global_worker
            flush = core is not None and not core._shutdown.is_set()
            # One module-flag check per period (the failpoints
            # discipline): with the timeline off and no worker to flush
            # to, the loop never walks the registry at all.
            if not (flush or telemetry.ENABLED):
                continue
            snaps = registry_snapshots()
            if not snaps:
                continue
            if telemetry.ENABLED:
                # Timeline sample rides the walk this loop already did
                # — no extra registry locking for the ring.
                telemetry.record_from_snapshots(snaps)
            if not flush:
                continue
            core.call(core.controller_addr, "kv_put",
                      {"ns": "metrics", "key": core.worker_id},
                      [json.dumps({"ts": time.time(),
                                   "metrics": snaps}).encode()],
                      timeout=10.0)
        except Exception:  # noqa: BLE001 - metrics must never crash work
            pass


def _after_fork_child() -> None:
    # The flusher THREAD does not survive fork, but the parent's handle
    # would make _ensure_flusher think it does.  Re-arm the locks FIRST
    # (a fork can land mid-snapshot, leaving the parent's lock state
    # poisoned in the child; the handler runs single-threaded, so
    # replacement is safe), then restart the flusher iff the child
    # inherited a populated registry — a child updating inherited
    # metrics through cached handles never calls a constructor, so
    # nothing else would revive the flush loop or the telemetry
    # sampling that rides it.
    global _flusher, _registry_lock
    _flusher = None
    _registry_lock = threading.Lock()
    for m in _registry.values():
        m._lock = threading.Lock()
    if _registry:
        _ensure_flusher()


os.register_at_fork(after_in_child=_after_fork_child)
