"""Utility layer: placement groups, scheduling strategies, actor pool,
distributed queue, collectives (analog of ray: python/ray/util/)."""
from ray_tpu.utils.actor_pool import ActorPool
from ray_tpu.utils.check_serialize import inspect_serializability
from ray_tpu.utils.placement_group import (placement_group,
                                           placement_group_table,
                                           remove_placement_group)
from ray_tpu.utils.queue import Queue
from ray_tpu.utils.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

__all__ = [
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "ActorPool", "Queue", "inspect_serializability",
]
