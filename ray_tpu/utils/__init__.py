"""Utility layer: placement groups, scheduling strategies, actor pool,
distributed queue, collectives (analog of ray: python/ray/util/)."""
from ray_tpu.utils.actor_pool import ActorPool
from ray_tpu.utils.check_serialize import inspect_serializability
from ray_tpu.utils.placement_group import (get_current_placement_group,
                                           get_placement_group,
                                           placement_group,
                                           placement_group_table,
                                           remove_placement_group)
from ray_tpu.utils.queue import Queue
from ray_tpu.utils.scheduling_strategies import (
    NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)
from ray_tpu.utils.serialization import (deregister_serializer,
                                         register_serializer)

_logged_once: set = set()


def log_once(key: str) -> bool:
    """True the first time `key` is seen in this process (ray:
    util/debug.py log_once)."""
    if key in _logged_once:
        return False
    _logged_once.add(key)
    return True


def get_node_ip_address() -> str:
    """This node's IP as the runtime uses it (ray: util
    get_node_ip_address).  Attached drivers/workers answer from their
    RPC address; otherwise fall back to a UDP-probe local address."""
    from ray_tpu._private.worker import _global_worker

    core = _global_worker
    if core is not None and core.address:
        return core.address.rsplit(":", 1)[0]
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def list_named_actors(all_namespaces: bool = False):
    """Names of live named actors (ray: util list_named_actors): the
    current namespace's names as strings, or [{namespace, name}] dicts
    with all_namespaces=True."""
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    ns = None if all_namespaces else core.namespace
    reply, _ = core.call(core.controller_addr, "list_named_actors",
                         {"namespace": ns}, timeout=30.0)
    if all_namespaces:
        return reply["named"]
    return [row["name"] for row in reply["named"]]


def __getattr__(name):
    if name == "collective":
        import importlib

        return importlib.import_module("ray_tpu.collective")
    raise AttributeError(f"module 'ray_tpu.utils' has no attribute {name!r}")


__all__ = [
    "placement_group", "remove_placement_group", "placement_group_table",
    "get_current_placement_group", "get_placement_group",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "ActorPool", "Queue", "inspect_serializability",
    "register_serializer", "deregister_serializer", "log_once",
    "get_node_ip_address", "list_named_actors", "collective",
]
