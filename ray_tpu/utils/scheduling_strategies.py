"""Scheduling strategy objects passed via options(scheduling_strategy=...).

Analog of ray: python/ray/util/scheduling_strategies.py:15,41,135.
"""
from __future__ import annotations

from dataclasses import dataclass

from ray_tpu.utils.placement_group import PlacementGroup


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False
