"""Scheduling strategy objects passed via options(scheduling_strategy=...).

Analog of ray: python/ray/util/scheduling_strategies.py:15,41,135.
"""
from __future__ import annotations

from dataclasses import dataclass

from ray_tpu.utils.placement_group import PlacementGroup


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: PlacementGroup
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


class In:
    """Label value must be one of `values`."""

    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def _lower(self) -> dict:
        return {"op": "in", "values": self.values}


class NotIn:
    def __init__(self, *values: str):
        self.values = [str(v) for v in values]

    def _lower(self) -> dict:
        return {"op": "notin", "values": self.values}


class Exists:
    def _lower(self) -> dict:
        return {"op": "exists"}


class DoesNotExist:
    def _lower(self) -> dict:
        return {"op": "absent"}


def _lower_constraints(d: dict | None) -> dict:
    """Operator objects -> plain msgpack-able dicts (a bare string or
    list is sugar for In)."""
    out = {}
    for k, v in (d or {}).items():
        if hasattr(v, "_lower"):
            out[str(k)] = v._lower()
        elif isinstance(v, (list, tuple)):
            out[str(k)] = {"op": "in", "values": [str(x) for x in v]}
        else:
            out[str(k)] = {"op": "in", "values": [str(v)]}
    return out


class NodeLabelSchedulingStrategy:
    """Schedule onto nodes by label (ray: util/scheduling_strategies.py
    :135 NodeLabelSchedulingStrategy).  On TPU this is the natural
    vehicle for accelerator-generation / slice-topology constraints —
    agents auto-label nodes with `ray_tpu.io/accelerator-type` and
    `ray_tpu.io/tpu-generation` (node_agent.detect_labels).

        NodeLabelSchedulingStrategy(
            hard={"ray_tpu.io/tpu-generation": In("v5e", "v6e")},
            soft={"zone": In("us-central2-b")})

    `hard` filters candidate nodes; `soft` prefers matching ones.
    """

    def __init__(self, hard: dict | None = None,
                 soft: dict | None = None):
        if not hard and not soft:
            raise ValueError(
                "NodeLabelSchedulingStrategy needs hard or soft "
                "constraints")
        self.hard = _lower_constraints(hard)
        self.soft = _lower_constraints(soft)
