"""OTLP trace export over the runtime's task-event timeline.

Analog of ray: python/ray/util/tracing/tracing_helper.py:1 — the
reference wraps task submission/execution in OpenTelemetry spans and
ships them through a user-configured exporter.  This runtime already
records W3C-style trace propagation on every task (worker.py task
header "trace": trace_id roots at the driver submission, span_id =
task id, parent_span = submitting task), so the bridge is a pure
transform: controller timeline events -> OTLP/JSON `resourceSpans`
(the OTLP/HTTP JSON encoding, usable by any collector's file receiver
or replayable against an OTLP endpoint).  No opentelemetry-sdk
dependency — the environment doesn't ship it; the JSON shape is the
contract.

Usage:
    ray_tpu.init()
    ... run tasks ...
    from ray_tpu.utils import tracing
    tracing.export_otlp_file("/tmp/spans.json")        # all spans
"""
from __future__ import annotations

import json
import time
from typing import Any

# Task states that open / close a span.
_OPEN = {"SUBMITTED", "PROFILE_BEGIN"}
_CLOSE = {"FINISHED", "FAILED", "PROFILE_END"}

_OK, _ERROR = 1, 2          # OTLP span status codes


def _hex_id(s: str, width: int) -> str:
    """OTLP ids are fixed-width lowercase hex (32 trace / 16 span)."""
    s = (s or "").lower()
    s = "".join(c for c in s if c in "0123456789abcdef")
    return (s + "0" * width)[:width]


def spans_from_events(events: list[dict]) -> list[dict]:
    """Pair open/close timeline events into OTLP span dicts.

    Unclosed spans (still-running tasks) are emitted with end == start
    and an `unfinished` attribute, so a trace captured mid-run is still
    valid OTLP.

    Events are time-sorted first (opens before closes at equal t): the
    controller's buffer interleaves per-worker push batches, so a
    worker's FINISHED can sit ahead of the driver's SUBMITTED in list
    order — pairing in raw order produced zero-duration spans plus a
    duplicate-id "unfinished" twin.
    """
    events = sorted(events, key=lambda e: (
        e["t"], 0 if e["state"] in _OPEN else 1))
    open_by_key: dict[tuple, dict] = {}
    spans: list[dict] = []
    for ev in events:
        key = (ev["task_id"], "PROFILE" if
               ev["state"].startswith("PROFILE") else "TASK")
        if ev["state"] in _OPEN:
            open_by_key[key] = ev
        elif ev["state"] in _CLOSE:
            begin = open_by_key.pop(key, ev)
            spans.append(_span(begin, ev))
    for key, begin in open_by_key.items():
        sp = _span(begin, begin)
        sp["attributes"].append(
            {"key": "ray_tpu.unfinished",
             "value": {"boolValue": True}})
        spans.append(sp)
    return spans


def _span(begin: dict, end: dict) -> dict:
    failed = end["state"] == "FAILED"
    name = begin.get("name") or begin["state"]
    if begin["state"] == "PROFILE_BEGIN":
        name = f"profile:{name}"
    else:
        name = f"task:{name}" if name else "task"
    return {
        "traceId": _hex_id(begin.get("trace_id", ""), 32),
        "spanId": _hex_id(begin["task_id"], 16),
        "parentSpanId": _hex_id(begin.get("parent", ""), 16)
        if begin.get("parent") else "",
        "name": name,
        "kind": 1,                      # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(begin["t"] * 1e9)),
        "endTimeUnixNano": str(int(end["t"] * 1e9)),
        "status": {"code": _ERROR if failed else _OK},
        "attributes": [
            {"key": "ray_tpu.task_id",
             "value": {"stringValue": begin["task_id"]}},
            {"key": "ray_tpu.worker_id",
             "value": {"stringValue": begin.get("worker", "")}},
            {"key": "ray_tpu.node_id",
             "value": {"stringValue": begin.get("node", "")}},
        ],
    }


def otlp_document(events: list[dict],
                  service_name: str = "ray_tpu") -> dict:
    """Full OTLP/JSON export document (the `resourceSpans` envelope a
    collector's OTLP/HTTP receiver accepts)."""
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": service_name}},
                {"key": "telemetry.sdk.name",
                 "value": {"stringValue": "ray_tpu.utils.tracing"}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu", "version": "1"},
                "spans": spans_from_events(events),
            }],
        }],
    }


def export_otlp_file(path: str, events: list[dict] | None = None,
                     service_name: str = "ray_tpu") -> int:
    """Export the cluster timeline (or an explicit event list) as one
    OTLP/JSON document at `path`; returns the span count."""
    if events is None:
        import ray_tpu

        events = ray_tpu.timeline()
    doc = otlp_document(events, service_name)
    spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(spans)


def otlp_from_recorder(spans_list: list[dict],
                       service_name: str = "ray_tpu") -> dict:
    """OTLP/JSON export document built from flight-recorder spans
    (`ray_tpu.tracing.harvest()` records) instead of task events — the
    same `resourceSpans` envelope, so both sources replay against one
    collector.  Recorder ids are already hex; `_hex_id` normalizes
    width (task ids are longer than recorder ids)."""
    otlp_spans = []
    for r in spans_list:
        attrs = [{"key": f"ray_tpu.{k}", "value": _attr_value(v)}
                 for k, v in (r.get("attrs") or {}).items()]
        attrs.append({"key": "ray_tpu.proc",
                      "value": {"stringValue":
                                str(r.get("proc", r.get("pid", "")))}})
        failed = bool((r.get("attrs") or {}).get("error"))
        otlp_spans.append({
            "traceId": _hex_id(r["tid"], 32),
            "spanId": _hex_id(r["sid"], 16),
            "parentSpanId": _hex_id(r["par"], 16) if r.get("par")
            else "",
            "name": r["name"],
            "kind": 1,
            "startTimeUnixNano": str(int(r["t0"] * 1e9)),
            "endTimeUnixNano": str(int(r["t1"] * 1e9)),
            "status": {"code": _ERROR if failed else _OK},
            "attributes": attrs,
        })
    return {
        "resourceSpans": [{
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": service_name}},
                {"key": "telemetry.sdk.name",
                 "value": {"stringValue": "ray_tpu.tracing"}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.flight_recorder",
                          "version": "1"},
                "spans": otlp_spans,
            }],
        }],
    }


def _attr_value(v: Any) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def export_otlp_http(endpoint: str, events: list[dict] | None = None,
                     service_name: str = "ray_tpu",
                     timeout: float = 10.0) -> int:
    """POST the export document to an OTLP/HTTP traces endpoint
    (`.../v1/traces`).  Offline environments use export_otlp_file; this
    is the same document over the wire."""
    import urllib.request

    if events is None:
        import ray_tpu

        events = ray_tpu.timeline()
    doc = otlp_document(events, service_name)
    body = json.dumps(doc).encode()
    req = urllib.request.Request(
        endpoint, data=body, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout):
        pass
    return len(doc["resourceSpans"][0]["scopeSpans"][0]["spans"])
