"""WorkerGroup: N gang-placed train-worker actors.

Analog of ray: python/ray/train/_internal/worker_group.py:102 (actors in a
placement group) + backend_executor's rendezvous.  Each TrainWorker is one
jax process (one per host on a pod — SURVEY §7: jax wants one process per
host owning all local chips); the train fn runs on a thread inside the
actor so the actor stays responsive for result polling and shutdown.
"""
from __future__ import annotations

import socket
import threading
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.train import session as session_mod
from ray_tpu.utils.placement_group import (PlacementGroup, placement_group,
                                           remove_placement_group)


class TrainWorker:
    """Actor: hosts one train process (rank) of the group."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._session = None
        self._finished = False
        self._error: str | None = None
        self._result: Any = None

    # --------------------------------------------------------- rendezvous
    def get_address(self) -> tuple[str, int]:
        """(ip, free_port) for the jax.distributed coordinator (worker 0)."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return socket.gethostbyname(socket.gethostname()), port

    def get_node_id(self) -> str:
        return ray_tpu.get_runtime_context().get_node_id()

    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary callable in the worker process (backend
        hooks, debugging probes)."""
        return fn(*args, **kwargs)

    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "object_store",
                              group_name: str = "train_host") -> int:
        """Join the trainer's host-side DCN collective group (ISSUE 5):
        the BackendExecutor forms one group across the worker gang so
        the train loop can sync host-side state (data-loader offsets,
        eval metrics, optimizer-shard exchanges) over the ring/tree
        schedules — `session.host_allreduce_async` overlaps that sync
        with the next step's input pipeline."""
        from ray_tpu import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)
        return rank

    def setup_env(self, env: dict[str, str]) -> bool:
        import os

        os.environ.update(env)
        return True

    # ---------------------------------------------------------- execution
    def start_train_fn(self, fn: Callable, config: dict, *,
                       world_rank: int, world_size: int, local_rank: int,
                       trial_name: str, checkpoint=None,
                       dataset_shards: dict | None = None,
                       host_group: str | None = None) -> bool:
        self._finished = False
        self._error = None
        self._result = None
        self._session = session_mod.init_session(
            world_rank=world_rank, world_size=world_size,
            local_rank=local_rank,
            node_id=ray_tpu.get_runtime_context().get_node_id(),
            trial_name=trial_name, checkpoint=checkpoint, config=config,
            dataset_shards=dataset_shards, host_group=host_group)

        def run():
            try:
                import inspect

                sig = inspect.signature(fn)
                self._result = fn(config) if len(
                    sig.parameters) >= 1 else fn()
            except StopIteration:
                pass
            except BaseException:  # noqa: BLE001
                self._error = traceback.format_exc()
            finally:
                # Async checkpoint writes must land before the loop is
                # declared done: an unflushed background save would race
                # the coordinator's final checkpoint collection — and a
                # FAILED write must surface as this rank's error, not
                # vanish (the flush re-raises the first failure).
                try:
                    from ray_tpu.train import checkpoint as ckpt_mod

                    ckpt_mod.flush_pending_writes()
                except Exception:  # noqa: BLE001
                    if self._error is None:
                        self._error = traceback.format_exc()
                self._finished = True
                self._session.out.put({"type": "done"})

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return True

    def next_result(self, timeout: float = 1.0) -> dict | None:
        """Drain one message from the session queue (None on timeout)."""
        import queue as q

        if self._session is None:
            return {"type": "done"}
        try:
            msg = self._session.out.get(timeout=timeout)
        except q.Empty:
            if self._finished:
                return {"type": "done"}
            return None
        return msg

    def get_status(self) -> dict:
        return {"finished": self._finished, "error": self._error}

    def get_result(self) -> Any:
        return self._result

    def stop(self) -> bool:
        if self._session is not None:
            self._session.stop_event.set()
        return True


class WorkerGroup:
    """Owns the PG + actors.  `execute` fans a callable to all workers."""

    def __init__(self, num_workers: int, bundles: list[dict],
                 strategy: str = "PACK",
                 pg: PlacementGroup | None = None):
        self.num_workers = num_workers
        self._own_pg = pg is None
        self.pg = pg or placement_group(bundles, strategy=strategy)
        if not self.pg.ready(timeout=120.0):
            raise RuntimeError(
                f"placement group {self.pg.id} not ready "
                f"(bundles={bundles}, strategy={strategy})")
        cls = ray_tpu.remote(TrainWorker)
        self.workers = [
            cls.options(
                num_cpus=0,     # resources held by the PG bundle
                placement_group=self.pg,
                placement_group_bundle_index=i).remote()
            for i in range(num_workers)
        ]

    def execute(self, method: str, *args, _timeout: float | None = None,
                **kwargs) -> list:
        """Call `method` on every worker, gather results."""
        return ray_tpu.get([getattr(w, method).remote(*args, **kwargs)
                            for w in self.workers], timeout=_timeout)

    def execute_async(self, method: str, *args, **kwargs) -> list:
        return [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers]

    def execute_single(self, idx: int, method: str, *args, **kwargs):
        return ray_tpu.get(
            getattr(self.workers[idx], method).remote(*args, **kwargs))

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []
        if self._own_pg:
            try:
                remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001
                pass
