"""WorkerGroup: N gang-placed train-worker actors.

Analog of ray: python/ray/train/_internal/worker_group.py:102 (actors in a
placement group) + backend_executor's rendezvous.  Each TrainWorker is one
jax process (one per host on a pod — SURVEY §7: jax wants one process per
host owning all local chips); the train fn runs on a thread inside the
actor so the actor stays responsive for result polling and shutdown.
"""
from __future__ import annotations

import socket
import threading
import traceback
from typing import Any, Callable

import ray_tpu
from ray_tpu.train import session as session_mod
from ray_tpu.utils.placement_group import (PlacementGroup, placement_group,
                                           remove_placement_group)


class TrainWorker:
    """Actor: hosts one train process (rank) of the group."""

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._session = None
        self._finished = False
        self._error: str | None = None
        self._result: Any = None

    # --------------------------------------------------------- rendezvous
    def get_address(self) -> tuple[str, int]:
        """(ip, free_port) for the jax.distributed coordinator (worker 0)."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return socket.gethostbyname(socket.gethostname()), port

    def get_node_id(self) -> str:
        return ray_tpu.get_runtime_context().get_node_id()

    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary callable in the worker process (backend
        hooks, debugging probes)."""
        return fn(*args, **kwargs)

    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "object_store",
                              group_name: str = "train_host") -> int:
        """Join the trainer's host-side DCN collective group (ISSUE 5):
        the BackendExecutor forms one group across the worker gang so
        the train loop can sync host-side state (data-loader offsets,
        eval metrics, optimizer-shard exchanges) over the ring/tree
        schedules — `session.host_allreduce_async` overlaps that sync
        with the next step's input pipeline."""
        from ray_tpu import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)
        return rank

    def setup_env(self, env: dict[str, str]) -> bool:
        import os

        os.environ.update(env)
        return True

    # ---------------------------------------------------------- execution
    def start_train_fn(self, fn: Callable, config: dict, *,
                       world_rank: int, world_size: int, local_rank: int,
                       trial_name: str, checkpoint=None,
                       dataset_shards: dict | None = None,
                       host_group: str | None = None,
                       epoch: int = 0, joined: bool = False) -> bool:
        self._finished = False
        self._error = None
        self._result = None
        self._session = sess = session_mod.init_session(
            world_rank=world_rank, world_size=world_size,
            local_rank=local_rank,
            node_id=ray_tpu.get_runtime_context().get_node_id(),
            trial_name=trial_name, checkpoint=checkpoint, config=config,
            dataset_shards=dataset_shards, host_group=host_group,
            epoch=epoch, joined=joined)

        def run():
            try:
                import inspect

                sig = inspect.signature(fn)
                self._result = fn(config) if len(
                    sig.parameters) >= 1 else fn()
            except StopIteration:
                pass
            except BaseException:  # noqa: BLE001
                # An incarnation interrupted at an elastic epoch barrier
                # unwinds however it can (collective error on the
                # drained group, StopIteration escaping a generator...):
                # that fallout is transition mechanics, not a failure.
                if not sess.epoch_abort:
                    self._error = traceback.format_exc()
            finally:
                # Async checkpoint writes must land before the loop is
                # declared done: an unflushed background save would race
                # the coordinator's final checkpoint collection — and a
                # FAILED write must surface as this rank's error, not
                # vanish (the flush re-raises the first failure).
                try:
                    from ray_tpu.train import checkpoint as ckpt_mod

                    ckpt_mod.flush_pending_writes()
                except Exception:  # noqa: BLE001
                    if self._error is None and not sess.epoch_abort:
                        self._error = traceback.format_exc()
                self._finished = True
                sess.out.put({"type": "done"})

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return True

    def next_result(self, timeout: float = 1.0) -> dict | None:
        """Drain one message from the session queue (None on timeout)."""
        import queue as q

        if self._session is None:
            return {"type": "done"}
        try:
            msg = self._session.out.get(timeout=timeout)
        except q.Empty:
            if self._finished:
                return {"type": "done"}
            return None
        return msg

    # ------------------------------------------------------ elastic epochs
    def park_at_barrier(self, epoch: int) -> bool:
        """First half of an elastic epoch transition (ISSUE 8): stop the
        running train fn at its next session touchpoint (report /
        host_allreduce / host_broadcast all raise StopIteration once the
        stop flag is up) and mark the incarnation as epoch-aborted so
        its unwind fallout never reads as a training failure.  The
        driver destroys the stale collective group right after this
        call, which unparks any rank blocked inside a collective."""
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            # Failpoint window: a survivor parking at the epoch barrier
            # (crash = the survivor dies mid-transition and the driver
            # must shrink further; delay = slow barrier, visible in
            # elastic_shrink_mttr_ms).
            failpoints.fire("train.epoch_barrier")
        s = self._session
        if s is not None:
            s.epoch_abort = True
            s.stop_event.set()
            # Unjam a report() blocked on the bounded outbound queue.
            import queue as q

            try:
                while True:
                    s.out.get_nowait()
            except q.Empty:
                pass
        return True

    def join_train(self, timeout: float = 20.0) -> dict:
        """Second half of the barrier: wait (bounded) for the train-fn
        thread to exit, draining the outbound queue so a blocked report
        can finish, then forget the stale epoch's collective group
        locally (the driver already destroyed the shared rendezvous).
        parked=False means the thread is wedged past the deadline — the
        driver treats that worker as lost."""
        import queue as q
        import time as _t

        t = self._thread
        s = self._session
        deadline = _t.monotonic() + timeout
        while t is not None and t.is_alive() and _t.monotonic() < deadline:
            if s is not None:
                try:
                    while True:
                        s.out.get_nowait()
                except q.Empty:
                    pass
            t.join(timeout=0.1)
        parked = t is None or not t.is_alive()
        if s is not None and s.host_group:
            from ray_tpu import collective as col

            col.deregister_collective_group(s.host_group)
        import os

        return {"parked": parked, "pid": os.getpid()}

    def get_status(self) -> dict:
        return {"finished": self._finished, "error": self._error}

    def get_result(self) -> Any:
        return self._result

    def stop(self) -> bool:
        if self._session is not None:
            self._session.stop_event.set()
        return True


class WorkerGroup:
    """Owns the PG + actors.  `execute` fans a callable to all workers.

    Elastic epochs (ISSUE 8) patch the group IN PLACE: `remove_worker`
    kills a slot's actor and eagerly releases its PG bundle (honest
    free capacity for the autoscaler and the regrow path);
    `restore_worker` places a fresh actor on a re-reserved bundle.
    Removed slots hold None — `execute` fans over live workers only."""

    def __init__(self, num_workers: int, bundles: list[dict],
                 strategy: str = "PACK",
                 pg: PlacementGroup | None = None):
        self.num_workers = num_workers
        self._own_pg = pg is None
        self.pg = pg or placement_group(bundles, strategy=strategy)
        if not self.pg.ready(timeout=120.0):
            raise RuntimeError(
                f"placement group {self.pg.id} not ready "
                f"(bundles={bundles}, strategy={strategy})")
        cls = ray_tpu.remote(TrainWorker)
        self.workers = [
            cls.options(
                num_cpus=0,     # resources held by the PG bundle
                placement_group=self.pg,
                placement_group_bundle_index=i).remote()
            for i in range(num_workers)
        ]

    def execute(self, method: str, *args, _timeout: float | None = None,
                **kwargs) -> list:
        """Call `method` on every live worker, gather results."""
        return ray_tpu.get([getattr(w, method).remote(*args, **kwargs)
                            for w in self.workers if w is not None],
                           timeout=_timeout)

    def execute_async(self, method: str, *args, **kwargs) -> list:
        return [getattr(w, method).remote(*args, **kwargs)
                for w in self.workers if w is not None]

    def execute_single(self, idx: int, method: str, *args, **kwargs):
        return ray_tpu.get(
            getattr(self.workers[idx], method).remote(*args, **kwargs))

    # ------------------------------------------------------ elastic patching
    def remove_worker(self, idx: int, release_bundle: bool = True) -> None:
        """Drop one slot: kill its actor (no-op if already dead) and
        eagerly release its PG bundle so the reservation doesn't sit on
        the agent until trial end (ISSUE-8 satellite — the autoscaler /
        regrow path must see honest free capacity)."""
        w = self.workers[idx]
        self.workers[idx] = None
        if w is not None:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001 - already dead
                pass
        if release_bundle:
            try:
                from ray_tpu.utils.placement_group import release_bundles

                release_bundles(self.pg, [idx])
            except Exception:  # noqa: BLE001 - node already reaped it
                pass

    def reschedule_lost_bundles(self) -> str:
        """Kick the controller's bundle scheduler for released slots
        (regrow step 1); returns the PG state."""
        from ray_tpu.utils.placement_group import \
            reschedule_placement_group

        return reschedule_placement_group(self.pg)

    def pg_state(self) -> str:
        from ray_tpu.utils.placement_group import placement_group_state

        return placement_group_state(self.pg)

    def restore_worker(self, idx: int):
        """Place a fresh TrainWorker on slot `idx`'s (re-reserved)
        bundle; the caller must confirm liveness before trusting it."""
        assert self.workers[idx] is None, f"slot {idx} still occupied"
        cls = ray_tpu.remote(TrainWorker)
        w = cls.options(num_cpus=0, placement_group=self.pg,
                        placement_group_bundle_index=idx).remote()
        self.workers[idx] = w
        return w

    def shutdown(self) -> None:
        for w in self.workers:
            if w is None:
                continue
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []
        if self._own_pg:
            try:
                remove_placement_group(self.pg)
            except Exception:  # noqa: BLE001
                pass
