"""Per-train-worker session: report(), get_checkpoint(), world topology.

Analog of ray: python/ray/train/_internal/session.py (:403 checkpoint
upload, :667 report).  The session lives inside the TrainWorker actor;
`report` hands (metrics, checkpoint) to the actor's outbound queue, which
the BackendExecutor drains (ray: backend_executor.get_next_results:572).
"""
from __future__ import annotations

import queue
import threading
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["_Session"] = None
_session_lock = threading.Lock()


class _Session:
    def __init__(self, world_rank: int, world_size: int, local_rank: int,
                 node_id: str, trial_name: str,
                 checkpoint: Checkpoint | None, config: dict,
                 dataset_shards: dict | None = None,
                 host_group: str | None = None,
                 epoch: int = 0, joined: bool = False):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_id = node_id
        self.trial_name = trial_name
        self.loaded_checkpoint = checkpoint
        self.config = config
        self.dataset_shards = dataset_shards or {}
        # Name of the gang-wide host-DCN collective group the
        # BackendExecutor formed over the workers (None for single-rank
        # runs — host_allreduce then degenerates to identity).
        self.host_group = host_group
        # Elastic membership (ISSUE 8): the monotonically increasing
        # epoch naming this gang roster, and whether THIS rank joined at
        # this epoch boundary (a regrown rank bootstraps its parameters
        # from rank 0 via host_broadcast instead of a checkpoint
        # reload).  epoch_abort marks an incarnation interrupted at an
        # epoch barrier: its unwind fallout (StopIteration escaping, a
        # collective erroring on the drained group) is transition
        # mechanics, not a training failure.
        self.epoch = epoch
        self.joined = joined
        self.epoch_abort = False
        self.out: queue.Queue = queue.Queue(maxsize=8)
        self.stop_event = threading.Event()
        # Per-step telemetry marks: wall time of the previous report()
        # feeds the gang's step-time series on the cluster timeline.
        self._last_report_t: float | None = None
        self._step_metrics = None

    def report(self, metrics: dict, checkpoint: Checkpoint | None) -> None:
        if self.stop_event.is_set():
            raise StopIteration("training stopped by the coordinator")
        # Failpoint window: a train worker at a step boundary, checkpoint
        # in hand but not yet handed to the coordinator (crash = worker
        # dies mid-step; the group restart must resume from the NEWEST
        # checkpoint that made it out).
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            failpoints.fire("train.step")
        self._mark_step()
        self.out.put({"type": "report", "metrics": dict(metrics),
                      "checkpoint": checkpoint, "rank": self.world_rank})

    def _mark_step(self) -> None:
        """Stage mark per report(): step wall time + a step counter as
        (trial, rank)-tagged metric series — the per-gang rows the
        telemetry timeline (`ray-tpu top`) samples every ~2s."""
        import time as _time

        now = _time.monotonic()
        last, self._last_report_t = self._last_report_t, now
        try:
            if self._step_metrics is None:
                from ray_tpu.utils import metrics as um

                tk = ("trial", "rank")
                self._step_metrics = {
                    "step_s": um.get_or_create(
                        um.Gauge, "train_step_s",
                        "Wall seconds between successive train "
                        "reports (per-gang step time)", tk),
                    "steps": um.get_or_create(
                        um.Counter, "train_reported_steps",
                        "train.report() calls", tk),
                }
            tags = {"trial": self.trial_name,
                    "rank": str(self.world_rank)}
            if last is not None:
                self._step_metrics["step_s"].set(now - last, tags)
            self._step_metrics["steps"].inc(1, tags)
        except Exception:  # noqa: BLE001 - telemetry never fails a step
            pass

    def drop_step_metrics(self) -> None:
        """Remove this session's (trial, rank) series from the metric
        registry (the Metric.remove discipline): the hosting process
        outlives sessions — an elastic re-form renumbers ranks on the
        SAME processes and a Tune run cycles trials, so an unremoved
        gauge would read as a live gang row forever."""
        if self._step_metrics is None:
            return
        try:
            tags = {"trial": self.trial_name,
                    "rank": str(self.world_rank)}
            for m in self._step_metrics.values():
                m.remove(tags)
        except Exception:  # noqa: BLE001 - teardown never fails
            pass


def init_session(**kwargs) -> _Session:
    global _session
    with _session_lock:
        if _session is not None:
            # Elastic re-form / next trial on the same process: the
            # outgoing incarnation's series must not linger.
            _session.drop_step_metrics()
        _session = _Session(**kwargs)
        return _session


def shutdown_session() -> None:
    global _session
    with _session_lock:
        if _session is not None:
            _session.drop_step_metrics()
        _session = None


def get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "not inside a train worker: ray_tpu.train.report/"
            "get_context must be called from the train loop")
    return _session


# ------------------------------------------------------------- public API
def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """Report metrics (+ optional checkpoint) from the train loop
    (ray: train.report)."""
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    """Checkpoint to resume from, if any (ray: train.get_checkpoint)."""
    return get_session().loaded_checkpoint


def get_dataset_shard(name: str = "train"):
    """This worker's split of the trainer's dataset (ray:
    train.get_dataset_shard — a DataIterator fed by streaming_split)."""
    return get_session().dataset_shards.get(name)


def host_allreduce(value, op: str = "sum"):
    """Allreduce host-side state (numpy/jax array) across the trainer's
    worker gang over the DCN collective plane (ISSUE 5: ring for large
    tensors, tree for small; gradients stay on ICI — this carries
    host-side state like metric sums and data-loader bookkeeping)."""
    return host_allreduce_async(value, op).wait()


def host_allreduce_async(value, op: str = "sum"):
    """Async host allreduce: returns a wait()-able CollectiveWork so
    the sync overlaps the next step's input pipeline:

        work = train.host_allreduce_async(step_metrics)
        batch = next(loader)          # overlaps the DCN exchange
        metrics = work.wait()
    """
    import numpy as np

    from ray_tpu import collective as col

    s = get_session()
    if s.stop_event.is_set():
        # Epoch-aware: a survivor parked at an elastic epoch barrier (or
        # a coordinator stop) must unwind NOW, not submit into a group
        # the driver is about to drain and destroy.
        raise StopIteration("training stopped by the coordinator")
    if s.host_group is None or s.world_size <= 1:
        class _Done:
            def __init__(self, v):
                # Copy, matching the collective contract: every real
                # path returns a fresh array, so single-rank callers
                # must not get an alias of their own (mutable) input.
                self._v = np.array(v, copy=True)

            def wait(self, timeout=None):
                return self._v
            result = wait

            def done(self):
                return True
        return _Done(value)
    return col.allreduce_async(value, group_name=s.host_group, op=op)


def host_broadcast(tree, src_rank: int = 0):
    """Broadcast a pytree of host arrays from `src_rank` across the
    trainer's gang (tree schedule over the DCN collective plane) and
    return it with rank `src_rank`'s leaf values everywhere.

    This is the elastic bootstrap (ISSUE 8): every rank calls it with a
    same-STRUCTURE tree right after building/restoring its initial
    state — a rank that JOINED the gang at this membership epoch
    receives the current parameters (and step counter) from rank 0
    instead of reloading a checkpoint, so regrow works even when the
    replacement host does not share the checkpoint filesystem.  For
    single-rank runs it degenerates to a defensive copy."""
    import jax
    import numpy as np

    from ray_tpu import collective as col
    from ray_tpu import failpoints

    s = get_session()
    if s.stop_event.is_set():
        raise StopIteration("training stopped by the coordinator")
    if failpoints.ACTIVE and s.joined:
        # Failpoint window: a JOINING rank mid-parameter-broadcast
        # (crash = the epoch must abort cleanly back to the surviving
        # roster; delay = slow join observable in regrow MTTR).
        failpoints.fire("train.rank_join")
    leaves, treedef = jax.tree.flatten(tree)
    if s.host_group is None or s.world_size <= 1:
        return jax.tree.unflatten(
            treedef, [np.array(np.asarray(x), copy=True) for x in leaves])
    out = [col.broadcast(np.asarray(x), src_rank=src_rank,
                         group_name=s.host_group) for x in leaves]
    return jax.tree.unflatten(treedef, out)


class TrainContext:
    """ray: train.get_context() — world topology of the running worker."""

    def get_world_rank(self) -> int:
        return get_session().world_rank

    def get_world_size(self) -> int:
        return get_session().world_size

    def get_local_rank(self) -> int:
        return get_session().local_rank

    def get_node_id(self) -> str:
        return get_session().node_id

    def get_trial_name(self) -> str:
        return get_session().trial_name

    def get_epoch(self) -> int:
        """Membership epoch of the current gang roster (ISSUE 8): bumps
        on every elastic shrink/regrow; 0 for the initial gang and for
        the whole run when elastic is off."""
        return get_session().epoch

    def get_joined(self) -> bool:
        """True iff THIS rank joined the gang at the current epoch
        boundary (a regrown replacement, expected to bootstrap its
        state via host_broadcast rather than a checkpoint reload)."""
        return get_session().joined


def get_context() -> TrainContext:
    return TrainContext()
