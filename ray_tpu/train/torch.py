"""TorchTrainer: distributed torch training on the CPU hosts of a pod.

Analog of ray: python/ray/train/torch/ (TorchTrainer torch_trainer.py,
_TorchBackend.on_start torch/config.py:65,150 — rendezvous + per-worker
dist.init_process_group; prepare_model/prepare_data_loader
train_loop_utils.py:12,158).

Role in the TPU framework: torch is the host-side path — CPU preprocessing
models, reference baselines, and parity for users migrating torch loops.
Device compute belongs to JaxTrainer (chips are jax-owned); the gloo
process group here is the host-collective plane, matching the reference's
CPU/gloo configuration.
"""
from __future__ import annotations

from typing import Callable

from ray_tpu.train.backend import Backend
from ray_tpu.train.trainer import DataParallelTrainer


def _torch_pg_init(master_addr: str, master_port: int, world_size: int,
                   rank: int, local_rank: int = 0,
                   local_world_size: int = 1) -> bool:
    """Runs inside each TrainWorker (ray: _setup_torch_process_group,
    torch/config.py:65).  Also exports the torchrun-style env vars: the
    torch ecosystem (transformers/accelerate) decides "am I
    distributed?" from RANK/WORLD_SIZE env, not from the live process
    group — without them an HF Trainer on 2 workers thinks both are
    process zero (no DDP, double checkpoint saves)."""
    import os

    import torch.distributed as dist

    os.environ.update({
        "MASTER_ADDR": master_addr,
        "MASTER_PORT": str(master_port),
        "RANK": str(rank),
        "WORLD_SIZE": str(world_size),
        "LOCAL_RANK": str(local_rank),
        "LOCAL_WORLD_SIZE": str(local_world_size),
    })
    if dist.is_initialized():
        return True
    dist.init_process_group(
        backend="gloo",
        init_method=f"tcp://{master_addr}:{master_port}",
        world_size=world_size, rank=rank)
    return True


def _torch_pg_shutdown() -> bool:
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


class TorchBackend(Backend):
    """Gloo process-group bring-up over the worker group."""

    def on_start(self, worker_group) -> None:
        n = worker_group.num_workers
        if n <= 1:
            return
        import ray_tpu

        ip, port = worker_group.execute_single(0, "get_address")
        # Local ranks: position within each node's worker list (same
        # derivation as BackendExecutor._run_once session wiring).
        node_ids = worker_group.execute("get_node_id")
        seen: dict[str, int] = {}
        local_ranks = []
        for nid in node_ids:
            local_ranks.append(seen.get(nid, 0))
            seen[nid] = local_ranks[-1] + 1
        local_sizes = [seen[nid] for nid in node_ids]
        ray_tpu.get([
            w.run_fn.remote(_torch_pg_init, ip, port, n, rank,
                            local_ranks[rank], local_sizes[rank])
            for rank, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group) -> None:
        try:
            worker_group.execute("run_fn", _torch_pg_shutdown,
                                 _timeout=10.0)
        except Exception:  # noqa: BLE001
            pass


class TorchTrainer(DataParallelTrainer):
    """Torch data-parallel trainer (ray: TorchTrainer)."""

    _backend_cls = TorchBackend


def prepare_model(model, parallel_strategy: str | None = "ddp"):
    """Wrap the model for the process group (ray: prepare_model
    train_loop_utils.py:158 — DDP/FSDP wrap + device move).  On this
    host-side path the device is CPU; with one worker the model is
    returned unwrapped."""
    import torch.distributed as dist

    if parallel_strategy is None or not dist.is_initialized() \
            or dist.get_world_size() <= 1:
        return model
    from torch.nn.parallel import DistributedDataParallel

    if parallel_strategy == "ddp":
        return DistributedDataParallel(model)
    if parallel_strategy == "fsdp":
        from torch.distributed.fsdp import FullyShardedDataParallel

        return FullyShardedDataParallel(model)
    raise ValueError(f"unknown parallel_strategy {parallel_strategy!r}")


def prepare_data_loader(data_loader):
    """Shard a DataLoader across the group with a DistributedSampler
    (ray: prepare_data_loader train_loop_utils.py:12).  Preserves the
    loader's own config (workers, pinning, collate, shuffle intent);
    custom batch_samplers cannot be re-sharded generically and pass
    through unchanged, as the reference does."""
    import torch.distributed as dist

    if not dist.is_initialized() or dist.get_world_size() <= 1:
        return data_loader
    from torch.utils.data import DataLoader, RandomSampler
    from torch.utils.data.distributed import DistributedSampler

    if data_loader.batch_size is None:
        # batch_sampler-driven loader: sharding it would break the user's
        # batching contract — leave it alone (the user shards manually).
        return data_loader
    ds = data_loader.dataset
    sampler = DistributedSampler(
        ds, num_replicas=dist.get_world_size(), rank=dist.get_rank(),
        # Keep the caller's ordering intent: sequential loaders (eval)
        # must not become shuffled.
        shuffle=isinstance(data_loader.sampler, RandomSampler))
    loader = DataLoader(ds, batch_size=data_loader.batch_size,
                        sampler=sampler,
                        num_workers=data_loader.num_workers,
                        pin_memory=data_loader.pin_memory,
                        collate_fn=data_loader.collate_fn,
                        worker_init_fn=data_loader.worker_init_fn,
                        generator=data_loader.generator,
                        drop_last=data_loader.drop_last)
    return _EpochTrackingLoader(loader)


class _EpochTrackingLoader:
    """Calls DistributedSampler.set_epoch per epoch automatically: without
    it every epoch replays one shuffle order (ray: prepare_data_loader's
    _WrappedDataLoader does the same)."""

    def __init__(self, loader):
        self._loader = loader
        self._epoch = 0

    def __iter__(self):
        self._loader.sampler.set_epoch(self._epoch)
        self._epoch += 1
        return iter(self._loader)

    def __len__(self):
        return len(self._loader)

    def __getattr__(self, name):
        return getattr(self._loader, name)


def backward(loss) -> None:
    """ray: train.torch.backward — plain backward on the CPU/gloo path."""
    loss.backward()


__all__ = ["TorchTrainer", "TorchBackend", "prepare_model",
           "prepare_data_loader", "backward"]
