"""HuggingFace Transformers integration for ray_tpu.train.

Analog of ray: python/ray/train/huggingface/transformers/
(_transformers_utils.py: RayTrainReportCallback.on_save copies the last
HF checkpoint into a Ray Train Checkpoint and reports log_history
metrics; prepare_trainer overrides get_train/eval_dataloader to feed Ray
Data iterables into transformers.Trainer).

Design differences from the reference:
- Ray wraps an already-created iterator object (its
  `_IterableFromIterator`); one epoch exhausts it.  Here the user passes
  the ray_tpu `DataIterator` itself as `train_dataset` and every epoch
  opens a FRESH `iter_torch_batches()` stream, so multi-epoch runs work
  without re-calling prepare.
- The checkpoint directory is copied to a persistent temp dir (our
  `Checkpoint` is a live path handle on the shared filesystem, not an
  uploaded artifact), and the batch size for Ray-fed loaders comes from
  `TrainingArguments.per_device_train_batch_size` instead of being fixed
  upstream.

Usage inside a TorchTrainer train loop::

    from ray_tpu.train.huggingface import (RayTrainReportCallback,
                                           prepare_trainer)
    trainer = transformers.Trainer(model, args,
                                   train_dataset=ray_data_iterator, ...)
    trainer.add_callback(RayTrainReportCallback())
    trainer = prepare_trainer(trainer)
    trainer.train()

With a `DataIterator` train_dataset (an IterableDataset under the hood),
set `TrainingArguments.max_steps` — transformers cannot derive epoch
length from a stream.
"""
from __future__ import annotations

import os
import shutil
import tempfile

from ray_tpu.data.iterator import DataIterator
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import report

try:  # transformers is an optional integration (baked into this env)
    from transformers.trainer_callback import TrainerCallback
except ImportError:  # pragma: no cover - env always has transformers
    TrainerCallback = object


class RayTrainReportCallback(TrainerCallback):
    """Report transformers checkpoints + metrics to ray_tpu.train.

    Fires after each `Trainer` checkpoint save: aggregates every dict in
    `TrainerState.log_history` (later entries win), copies the newest HF
    checkpoint directory into a ray_tpu `Checkpoint`, and calls
    `train.report(metrics, checkpoint)` — from a worker that lands in
    the worker group's result queue exactly like a hand-written loop's
    report (ray: RayTrainReportCallback.on_save).
    """

    CHECKPOINT_NAME = "checkpoint"

    def on_save(self, args, state, control, **kwargs):
        metrics = {}
        for log in state.log_history:
            metrics.update(log)
        checkpoint = None
        src = _last_checkpoint_dir(args.output_dir)
        if src is not None:
            # Persistent dir, not a context-managed one: the Checkpoint
            # handle stays valid after this callback returns.  The
            # ephemeral marker hands ownership to CheckpointManager,
            # which deletes this source copy once it lands in the run's
            # storage dir — without it every save would leak a full
            # model snapshot under /tmp.
            dst = tempfile.mkdtemp(prefix="raytpu-hf-ckpt-")
            shutil.copytree(src, os.path.join(dst, self.CHECKPOINT_NAME))
            Checkpoint.mark_ephemeral(dst)
            checkpoint = Checkpoint.from_directory(dst)
        report(metrics, checkpoint=checkpoint)


def _last_checkpoint_dir(output_dir: str) -> str | None:
    """Newest `checkpoint-<step>` subdirectory, None if none exist."""
    try:
        candidates = [
            d for d in os.listdir(output_dir)
            if d.startswith("checkpoint-")
            and d.split("-")[-1].isdigit()
            and os.path.isdir(os.path.join(output_dir, d))
        ]
    except FileNotFoundError:
        return None
    if not candidates:
        return None
    newest = max(candidates, key=lambda d: int(d.split("-")[-1]))
    return os.path.join(output_dir, newest)


def prepare_trainer(trainer):
    """Wire ray_tpu Data iterators into a transformers.Trainer.

    When `train_dataset` / `eval_dataset` is a ray_tpu `DataIterator`,
    the returned trainer's dataloaders pull batches from
    `iter_torch_batches(batch_size=per_device_train_batch_size)` — a
    fresh stream per epoch — instead of torch's sampler machinery
    (which needs a map-style dataset).  Anything else falls through to
    the stock transformers dataloaders untouched.
    """
    try:
        import transformers  # noqa: F401
        from torch.utils.data import DataLoader, IterableDataset
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "prepare_trainer requires transformers and torch") from e

    class _RayStream(IterableDataset):
        """Re-iterable view: each epoch opens a fresh batch stream."""

        def __init__(self, it: DataIterator, batch_size: int):
            self._it = it
            self._batch_size = batch_size

        def __iter__(self):
            return iter(self._it.iter_torch_batches(
                batch_size=self._batch_size))

    base = trainer.__class__

    class _RayTransformersTrainer(base):
        def get_train_dataloader(self):
            if isinstance(self.train_dataset, DataIterator):
                stream = _RayStream(
                    self.train_dataset,
                    self.args.per_device_train_batch_size)
                # Batches arrive pre-collated from iter_torch_batches.
                return DataLoader(stream, batch_size=1,
                                  collate_fn=lambda x: x[0])
            return super().get_train_dataloader()

        def get_eval_dataloader(self, eval_dataset=None):
            ds = eval_dataset if eval_dataset is not None \
                else self.eval_dataset
            if isinstance(ds, DataIterator):
                stream = _RayStream(
                    ds, self.args.per_device_eval_batch_size)
                return DataLoader(stream, batch_size=1,
                                  collate_fn=lambda x: x[0])
            return super().get_eval_dataloader(eval_dataset)

    trainer.__class__ = _RayTransformersTrainer
    return trainer
