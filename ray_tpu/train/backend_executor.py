"""BackendExecutor: drives a WorkerGroup through a training run.

Analog of ray: python/ray/train/_internal/backend_executor.py:67
(start :129, start_training :445, get_next_results :572, _restart
:740-756).  Responsibilities: gang-place workers, run the backend
rendezvous, launch the user train fn everywhere, drain per-worker report
streams in lock-step, and recover from worker failure.

Recovery paths (ISSUE 8):
- **Elastic** (default, >= 2 workers): membership epochs — shrink to the
  surviving processes and resume from the newest async checkpoint, then
  regrow when capacity returns (train/elastic.py; SURVEY §7 "elastic
  restart with slice granularity" made rank-granular).
- **Legacy restart loop** (RAY_TPU_ELASTIC=0, or single worker): tear
  the whole group down and respawn, up to FailureConfig.max_failures —
  with one refinement: when every worker is still ALIVE (a transient
  train-fn error), the live gang is reused instead of respawned.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable

import ray_tpu
from ray_tpu.exceptions import ActorError, WorkerCrashedError
from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    pass


def _dataset_shards(config: dict, n: int) -> tuple[list[dict], dict]:
    """Per-worker dataset iterators + the config with the dataset keys
    stripped.  One streaming_split iterator per worker per split
    dataset (ray: DataParallelTrainer wiring train.get_dataset_shard
    through the data StreamSplitDataIterator); called per gang launch,
    so an elastic epoch re-splits at the new world size."""
    shards_per_worker: list[dict] = [{} for _ in range(n)]
    to_split = config.get("_datasets_to_split", "all")
    if isinstance(to_split, str) and to_split != "all":
        to_split = [to_split]    # membership, never substring match
    for name, ds in (config.get("_datasets") or {}).items():
        if to_split == "all" or name in to_split:
            its = ds.streaming_split(n)
            for i in range(n):
                shards_per_worker[i][name] = its[i]
        else:
            # Unsplit datasets replicate: every worker iterates the
            # whole thing (ray: DataConfig.datasets_to_split).
            for i in range(n):
                shards_per_worker[i][name] = ds.iterator()
    config = {k: v for k, v in config.items()
              if k not in ("_datasets", "_datasets_to_split")}
    return shards_per_worker, config


class BackendExecutor:
    def __init__(self, scaling: ScalingConfig,
                 backend: Backend | None = None,
                 failure: FailureConfig | None = None,
                 trial_name: str = "train"):
        self.scaling = scaling
        self.backend = backend or JaxBackend()
        self.failure = failure or FailureConfig()
        self.trial_name = trial_name
        self.worker_group: WorkerGroup | None = None
        self._num_failures = 0
        # Elastic introspection (ISSUE 8): the ElasticRun driving this
        # executor (None on the legacy path), and the legacy restart
        # loop's failure→relaunched wall time for the same-run MTTR A/B.
        self.elastic = None
        self.restart_mttr_ms: float | None = None
        self._fail_t0: float | None = None

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.worker_group = WorkerGroup(
            self.scaling.num_workers, self.scaling.bundles(),
            strategy=self.scaling.placement_strategy)
        self.backend.on_start(self.worker_group)

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group)
            except Exception:  # noqa: BLE001
                pass
            if self.worker_group.num_workers >= 2:
                # The host collective group's detached rendezvous would
                # otherwise outlive the run (the round-10
                # destroy_collective_group works from the driver even
                # though the group's registries live in the workers).
                try:
                    from ray_tpu import collective as col

                    col.destroy_collective_group(
                        getattr(self, "_host_group",
                                f"train_host:{self.trial_name}"))
                except Exception:  # noqa: BLE001
                    pass
            self.worker_group.shutdown()
            self.worker_group = None

    def _workers_all_alive(self) -> bool:
        """Ping every worker of the current group (short deadline): True
        iff all answer — the reuse-don't-respawn gate of the legacy
        retry path."""
        wg = self.worker_group
        if wg is None or not wg.workers or any(
                w is None for w in wg.workers):
            return False
        try:
            wg.execute("get_status", _timeout=10.0)
            return True
        except Exception:  # noqa: BLE001 - someone is dead/wedged
            return False

    def _quiesce_group(self) -> bool:
        """Prepare a live gang for in-place reuse: park every worker's
        train fn (a previous incarnation's thread still unwinding after
        start_train_fn resets worker state would poison the retry with
        a phantom error), destroy the stale collective group (a
        same-name re-create needs a fresh rendezvous, and the destroy
        unparks any rank still blocked in a collective), then join the
        fn threads.  False → the caller falls back to a full restart."""
        wg = self.worker_group
        try:
            wg.execute("park_at_barrier", 0, _timeout=30.0)
            from ray_tpu import collective as col

            try:
                col.destroy_collective_group(
                    getattr(self, "_host_group",
                            f"train_host:{self.trial_name}"))
            except Exception:  # noqa: BLE001 - never formed (1 worker)
                pass
            return all(st["parked"] for st in wg.execute(
                "join_train", 20.0, _timeout=40.0))
        except Exception:  # noqa: BLE001 - someone died after the ping
            return False

    def _restart(self) -> None:
        # Failpoint window: the group-restart path itself (delay = slow
        # recovery observable in MTTR; error = restart refused).
        from ray_tpu import failpoints

        if failpoints.ACTIVE:
            failpoints.fire("train.group_restart")
        logger.warning("restarting worker group (failure %d)",
                       self._num_failures)
        self.shutdown()
        self.start()

    # ------------------------------------------------------------ training
    def run(self, train_fn: Callable, config: dict | None = None,
            on_report: Callable[[list[dict]], Any] | None = None,
            resume_checkpoint: Checkpoint | None = None,
            latest_checkpoint: Callable[[], Checkpoint | None]
            | None = None) -> list:
        """Run train_fn on all workers to completion.  `on_report` sees the
        per-round list of rank reports (aligned, one per worker) and may
        return "stop" to early-stop.  Returns per-worker return values.

        `latest_checkpoint` (ray: backend_executor.py:740-756 pairs
        _restart with the session's newest checkpoint): after a group
        restart the retry resumes from the NEWEST checkpoint reported so
        far, not the run's original resume point — without it a failure
        at step 900/1000 replays from step 0.
        """
        config = config or {}
        self._host_group = f"train_host:{self.trial_name}"
        if self.scaling.num_workers >= 2:
            # Elastic membership epochs (ISSUE 8): shrink to survivors
            # on rank loss, regrow at an epoch boundary.  Kill switch
            # RAY_TPU_ELASTIC=0 (read here, per run) keeps the legacy
            # restart loop below for same-run A/B.
            from ray_tpu.train import elastic

            if elastic.elastic_enabled():
                self.elastic = elastic.ElasticRun(self)
                return self.elastic.run(train_fn, config, on_report,
                                        resume_checkpoint,
                                        latest_checkpoint)
        max_failures = self.failure.max_failures
        while True:
            resume = resume_checkpoint
            if latest_checkpoint is not None:
                resume = latest_checkpoint() or resume_checkpoint
            try:
                return self._run_once(train_fn, config, on_report,
                                      resume)
            except (TrainingFailedError, ActorError,
                    WorkerCrashedError) as e:
                # Any actor/worker failure inside a run round counts as a
                # training failure: raw ActorError can surface from
                # group-wide calls (get_status/get_result/execute) when a
                # worker dies between result polls — same recovery.
                if not isinstance(e, TrainingFailedError):
                    e = TrainingFailedError(f"worker group failure: {e!r}")
                self._num_failures += 1
                if max_failures >= 0 and self._num_failures > max_failures:
                    raise e from None
                self._fail_t0 = time.perf_counter()
                if self._workers_all_alive() and self._quiesce_group():
                    # ISSUE-8 satellite: a transient train-fn error with
                    # every worker still alive (e.g. one rank's step
                    # raised) does not need a gang respawn — quiesce the
                    # live processes and reuse them.
                    logger.warning(
                        "retrying on the surviving worker group "
                        "(failure %d: all workers alive)",
                        self._num_failures)
                else:
                    self._restart()

    def _run_once(self, train_fn, config, on_report,
                  resume_checkpoint) -> list:
        wg = self.worker_group
        if wg is None:
            raise RuntimeError("executor not started")
        n = wg.num_workers
        # local ranks: position within each node's worker list
        node_ids = wg.execute("get_node_id")
        seen: dict[str, int] = {}
        local_ranks = []
        for nid in node_ids:
            local_ranks.append(seen.get(nid, 0))
            seen[nid] = local_ranks[-1] + 1
        self.backend.on_training_start(wg)
        # Host-side DCN collective group over the gang (ISSUE 5): the
        # train loop syncs host state through session.host_allreduce
        # (ring/tree schedules, async overlap) instead of bespoke RPCs.
        host_group = None
        if n >= 2:
            from ray_tpu import collective as col

            host_group = getattr(self, "_host_group",
                                 f"train_host:{self.trial_name}")
            col.create_collective_group(wg.workers, n, list(range(n)),
                                        group_name=host_group)
        shards_per_worker, config = _dataset_shards(config, n)
        ray_tpu.get([
            w.start_train_fn.remote(
                train_fn, config, world_rank=i, world_size=n,
                local_rank=local_ranks[i], trial_name=self.trial_name,
                checkpoint=resume_checkpoint,
                dataset_shards=shards_per_worker[i],
                host_group=host_group)
            for i, w in enumerate(wg.workers)
        ])
        if self._fail_t0 is not None:
            # Legacy restart loop's MTTR: failure caught → whole gang
            # relaunched (the elastic path's same-run A/B reference).
            self.restart_mttr_ms = round(
                (time.perf_counter() - self._fail_t0) * 1e3, 1)
            self._fail_t0 = None

        done = [False] * n
        pending: list[list[dict]] = [[] for _ in range(n)]
        while not all(done):
            progressed = False
            for i, w in enumerate(wg.workers):
                if done[i] or pending[i]:
                    continue
                try:
                    msg = ray_tpu.get(w.next_result.remote(timeout=1.0),
                                      timeout=60.0)
                except Exception as e:  # noqa: BLE001 - worker death
                    raise TrainingFailedError(
                        f"worker {i} died: {e!r}") from e
                if msg is None:
                    continue
                progressed = True
                if msg["type"] == "done":
                    done[i] = True
                elif msg["type"] == "report":
                    pending[i].append(msg)
            # lock-step: emit a round once every live worker reported
            if all(p or done[i] for i, p in enumerate(pending)) and \
                    any(pending):
                round_msgs = [p.pop(0) if p else None for p in pending]
                if on_report is not None:
                    verdict = on_report(
                        [m for m in round_msgs if m is not None])
                    if verdict == "stop":
                        wg.execute("stop")
            if not progressed:
                time.sleep(0.05)

        statuses = wg.execute("get_status")
        errors = [(i, s["error"]) for i, s in enumerate(statuses)
                  if s["error"]]
        if errors:
            rank, tb = errors[0]
            raise TrainingFailedError(
                f"train fn failed on rank {rank}:\n{tb}")
        return wg.execute("get_result")
