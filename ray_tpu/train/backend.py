"""Training backends: per-worker collective/runtime setup hooks.

Analog of ray: python/ray/train/backend.py (Backend.on_start/on_shutdown)
and torch/config.py:65,150 (_TorchBackend.on_start = pick rendezvous addr,
dist.init_process_group on every worker).

TPU difference (SURVEY §2.4 "Collective backend"): inside a slice there is
no process-group object to build — XLA schedules ICI collectives from the
jit'd program.  The backend's only job is the *multi-host* jax runtime
rendezvous: worker 0 donates coordinator ip:port, every worker calls
jax.distributed.initialize(coordinator, num_processes, process_id), after
which jax.devices() spans the whole slice and pjit programs are global.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train.worker_group import WorkerGroup


class BackendConfig:
    """Declarative backend selector (ray: train/backend.py
    BackendConfig): subclasses name the Backend that implements their
    setup via backend_cls."""

    @property
    def backend_cls(self) -> type:
        return Backend


class Backend:
    def on_start(self, worker_group: "WorkerGroup") -> None:  # noqa: B027
        pass

    def on_shutdown(self, worker_group: "WorkerGroup") -> None:  # noqa: B027
        pass

    def on_training_start(self, worker_group: "WorkerGroup") -> None:  # noqa: B027,E501
        pass


def _jax_distributed_init(coordinator: str, num_processes: int,
                          process_id: int) -> bool:
    """Runs inside each TrainWorker actor."""
    import jax

    if num_processes == 1:
        return True          # single process: local devices already global
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


class JaxBackend(Backend):
    """Multi-host jax runtime bring-up over the worker group."""

    def on_start(self, worker_group: "WorkerGroup") -> None:
        n = worker_group.num_workers
        if n <= 1:
            return
        ip, port = worker_group.execute_single(0, "get_address")
        coordinator = f"{ip}:{port}"
        import ray_tpu

        ray_tpu.get([
            w.run_fn.remote(_jax_distributed_init, coordinator, n, i)
            for i, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: "WorkerGroup") -> None:
        def _shut():
            import jax

            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass
            return True

        try:
            worker_group.execute("run_fn", _shut, _timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
