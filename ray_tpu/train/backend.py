"""Training backends: per-worker collective/runtime setup hooks.

Analog of ray: python/ray/train/backend.py (Backend.on_start/on_shutdown)
and torch/config.py:65,150 (_TorchBackend.on_start = pick rendezvous addr,
dist.init_process_group on every worker).

TPU difference (SURVEY §2.4 "Collective backend"): inside a slice there is
no process-group object to build — XLA schedules ICI collectives from the
jit'd program.  The backend's only job is the *multi-host* jax runtime
rendezvous: worker 0 donates coordinator ip:port, every worker calls
jax.distributed.initialize(coordinator, num_processes, process_id), after
which jax.devices() spans the whole slice and pjit programs are global.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train.worker_group import WorkerGroup


class BackendConfig:
    """Declarative backend selector (ray: train/backend.py
    BackendConfig): subclasses name the Backend that implements their
    setup via backend_cls."""

    @property
    def backend_cls(self) -> type:
        return Backend


class Backend:
    def on_start(self, worker_group: "WorkerGroup") -> None:  # noqa: B027
        pass

    def on_shutdown(self, worker_group: "WorkerGroup") -> None:  # noqa: B027
        pass

    def on_training_start(self, worker_group: "WorkerGroup") -> None:  # noqa: B027,E501
        pass

    def on_epoch_start(self, workers: list, epoch: int) -> None:  # noqa: B027,E501
        """Elastic membership change (ISSUE 8): `workers` is the NEW
        roster in rank order (survivors first, joiners appended).  The
        backend re-forms whatever per-gang runtime it owns at the new
        world size; the base backend owns nothing."""
        pass


def _jax_distributed_init(coordinator: str, num_processes: int,
                          process_id: int,
                          survivable: bool = False) -> bool:
    """Runs inside each TrainWorker actor.

    `survivable` (elastic gangs, ISSUE 8): the default XLA coordination
    client LOG(QFATAL)s the whole process when any task misses
    heartbeats ("Terminating process because the JAX distributed
    service detected fatal errors") — one preempted host becomes a
    gang-wide massacre, which is exactly what the membership-epoch
    protocol exists to avoid.  For the duration of initialize() the
    client factory is patched to install a log-only callback, disable
    shutdown-on-destruction (a dropped half-shut client must not block
    in its destructor), and bound the shutdown barrier at seconds, not
    the 5-minute default (a dead peer fails the barrier — survivors
    must not serve a 5-minute sentence for it at every epoch change).
    """
    import jax

    if num_processes == 1:
        return True          # single process: local devices already global
    if not survivable:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return True
    import logging as _logging

    from jax._src import distributed as jdist

    orig = jdist.xla_extension.get_distributed_runtime_client

    def _factory(addr, node_id, **kw):
        kw["missed_heartbeat_callback"] = lambda *a: _logging.getLogger(
            __name__).warning(
            "jax coordination heartbeat failure (surviving: the elastic "
            "epoch transition re-forms the gang): %s", a)
        kw["shutdown_on_destruction"] = False
        kw["shutdown_timeout"] = 5
        return orig(addr, node_id, **kw)

    jdist.xla_extension.get_distributed_runtime_client = _factory
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    finally:
        jdist.xla_extension.get_distributed_runtime_client = orig
    return True


def _jax_distributed_teardown() -> bool:
    """Dismantle this process's jax.distributed state even when the old
    gang is half-dead: a dead peer fails the shutdown barrier, and the
    orderly path leaves the module state set (so a later initialize
    raises 'should only be called once') — force-drop the handles."""
    import jax
    from jax._src import distributed as jdist

    try:
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 - barrier failed / never initialized
        state = jdist.global_state
        for attr in ("client", "service", "preemption_sync_manager"):
            try:
                setattr(state, attr, None)
            except Exception:  # noqa: BLE001
                pass
    return True


def _jax_distributed_reinit(coordinator: str, num_processes: int,
                            process_id: int) -> bool:
    """Epoch transition on a SURVIVING process: tear down the previous
    incarnation's distributed runtime (its world no longer exists) and
    re-join at the new size.  A fresh joiner has nothing to shut down —
    the call degrades to a plain initialize."""
    _jax_distributed_teardown()
    return _jax_distributed_init(coordinator, num_processes, process_id,
                                 survivable=True)


class JaxBackend(Backend):
    """Multi-host jax runtime bring-up over the worker group."""

    def on_start(self, worker_group: "WorkerGroup") -> None:
        n = worker_group.num_workers
        if n <= 1:
            return
        ip, port = worker_group.execute_single(0, "get_address")
        coordinator = f"{ip}:{port}"
        import ray_tpu
        from ray_tpu.train.elastic import elastic_enabled

        ray_tpu.get([
            w.run_fn.remote(_jax_distributed_init, coordinator, n, i,
                            elastic_enabled())
            for i, w in enumerate(worker_group.workers)
        ])

    def on_epoch_start(self, workers: list, epoch: int) -> None:
        """Re-form the multi-host jax runtime over the new roster: the
        new rank 0 donates a fresh coordinator port, every member
        shutdown+initializes at the new world size.  Failure aborts the
        epoch transition (the driver falls back to a full restart) —
        silently continuing with a stale device world would make the
        first global pjit hang."""
        n = len(workers)
        if n <= 1:
            # Shrink to one process: drop the stale distributed state so
            # local devices are the whole world again.
            import ray_tpu

            try:
                ray_tpu.get([w.run_fn.remote(_jax_distributed_reinit,
                                             "", 1, 0) for w in workers],
                            timeout=30.0)
            except Exception:  # noqa: BLE001 - best effort at world 1
                pass
            return
        import ray_tpu

        ip, port = ray_tpu.get(workers[0].get_address.remote(),
                               timeout=30.0)
        coordinator = f"{ip}:{port}"
        ray_tpu.get([
            w.run_fn.remote(_jax_distributed_reinit, coordinator, n, i)
            for i, w in enumerate(workers)
        ], timeout=120.0)

    def on_shutdown(self, worker_group: "WorkerGroup") -> None:
        def _shut():
            import jax

            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass
            return True

        try:
            worker_group.execute("run_fn", _shut, _timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
