"""ray_tpu.train — distributed training library (reference: python/ray/train).

Layers:
  - step: pure-jax sharded train/eval steps (dp/fsdp/tp/sp as layouts)
  - worker_group / backend / backend_executor: gang-placed jax processes,
    multi-host rendezvous, report plumbing, group restart on failure
  - trainer: JaxTrainer(...).fit() -> Result
  - session: report()/get_checkpoint()/get_context() inside the loop
"""
from ray_tpu.train.backend import Backend, BackendConfig  # noqa: F401
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager  # noqa: F401
from ray_tpu.train.config import (CheckpointConfig, DataConfig,  # noqa: F401
                                  FailureConfig, SyncConfig,
                                  RunConfig, ScalingConfig)
from ray_tpu.train.gbdt import LightGBMTrainer, XGBoostTrainer  # noqa: F401
from ray_tpu.train.session import (get_checkpoint, get_context,  # noqa: F401
                                   get_dataset_shard, host_allreduce,
                                   host_allreduce_async, host_broadcast,
                                   report)
from ray_tpu.train.step import (TrainState, create_train_state,  # noqa: F401
                                make_train_step, reshard_state,
                                sharded_init, sharded_train_step)
from ray_tpu.train.trainer import (BaseTrainer, DataParallelTrainer,  # noqa: F401,E501
                                   JaxTrainer, Result)
from ray_tpu.train import torch  # noqa: F401  (TorchTrainer lives here)
