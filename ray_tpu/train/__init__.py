"""ray_tpu.train — distributed training library (reference: python/ray/train).

Two layers:
  - `ray_tpu.train.step`: pure-jax sharded train/eval steps (no control
    plane) — the compute core every trainer drives.
  - trainer/session/worker-group layers (reference: base_trainer.py,
    backend_executor.py, worker_group.py) built on ray_tpu actors.
"""
from ray_tpu.train.step import (  # noqa: F401
    TrainState,
    create_train_state,
    make_train_step,
    sharded_init,
    sharded_train_step,
)
