"""Train/run configuration dataclasses.

Analog of the reference's air configs (ray: python/ray/air/config.py:103
ScalingConfig, :399 FailureConfig; python/ray/train/CheckpointConfig) with
TPU-native resource vocabulary: workers map to hosts of a slice, each
worker owning all local chips (jax's one-process-per-host model,
SURVEY §7 "Multi-host jax process model").
"""
from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class ScalingConfig:
    """How many train workers and what each reserves.

    num_workers: processes (1 per host on a pod). use_tpu: reserve the
    node's chips. resources_per_worker: extra custom resources.
    topology: optional slice topology string (e.g. "v5e-64") used as a
    gang resource so all workers land on one slice (the analog of the
    reference's TPU pod-name resource, ray:
    python/ray/_private/accelerators/tpu.py get_current_pod_name).
    """

    num_workers: int = 1
    use_tpu: bool = False
    num_cpus_per_worker: float = 1.0
    num_tpus_per_worker: float = 0.0
    resources_per_worker: dict[str, float] | None = None
    topology: str | None = None
    placement_strategy: str = "PACK"

    def bundle(self) -> dict[str, float]:
        b: dict[str, float] = {"CPU": self.num_cpus_per_worker}
        if self.use_tpu or self.num_tpus_per_worker:
            b["TPU"] = self.num_tpus_per_worker or 1.0
        if self.topology:
            b[f"tpu-slice:{self.topology}"] = 1.0
        # CPU/TPU in resources_per_worker OVERRIDE the defaults (the
        # reference's ScalingConfig semantics); anything else is an extra
        # custom resource.  Summing CPU here once double-reserved every
        # bundle ({"CPU": 1} -> 2.0), and a worker group that grabs the
        # whole cluster deadlocks any train loop that also consumes a
        # streaming dataset (the data tasks have nowhere to run).
        b.update(self.resources_per_worker or {})
        return b

    def bundles(self) -> list[dict[str, float]]:
        return [self.bundle() for _ in range(self.num_workers)]


@dataclasses.dataclass
class FailureConfig:
    """max_failures: worker-group restarts before giving up (-1 = infinite)
    (ray: FailureConfig air/config.py:399; BackendExecutor._restart)."""

    max_failures: int = 0
    fail_fast: bool = False


@dataclasses.dataclass
class CheckpointConfig:
    """Bound + rank persisted checkpoints (ray: CheckpointConfig)."""

    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclasses.dataclass
class RunConfig:
    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig | None = None
    checkpoint_config: CheckpointConfig | None = None
    stop: dict[str, Any] | None = None
    verbose: int = 1
    # Tune experiment-loop callbacks (ray: RunConfig.callbacks); a
    # ProgressReporter is a callback here (progress_reporter.py).
    callbacks: list | None = None
    # Accepted for API parity; storage is the local/shared filesystem at
    # storage_path, so there is nothing to sync (ray: SyncConfig drives
    # driver<->cloud uploads).
    sync_config: "SyncConfig | None" = None


@dataclasses.dataclass
class DataConfig:
    """Which datasets split across train workers (ray:
    train/_internal/data_config.py).  Datasets named here shard via
    streaming_split; others are passed whole to every worker."""
    datasets_to_split: "list[str] | str" = "all"


@dataclasses.dataclass
class SyncConfig:
    """ray: train/_internal/syncer.py SyncConfig — retained fields only;
    syncing is a no-op because checkpoints/results already land on the
    shared storage_path filesystem."""
    sync_period: float = 300.0
    sync_timeout: float = 1800.0
    sync_artifacts: bool = False
