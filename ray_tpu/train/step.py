"""Sharded training step: the compute core of ray_tpu.train.

The reference's Train library never owns the step — users write torch loops
and ray wraps DDP around them (ray: python/ray/train/torch/train_loop_utils.py:158).
Here the framework owns an XLA-native step: loss/grad/optimizer fused into
one jitted program whose parallelism (dp/fsdp/tp/sp) is purely a layout
choice from ray_tpu.parallel.sharding — XLA inserts the ICI collectives
(psum for grads under dp, all-gather/reduce-scatter for fsdp params under
GSPMD, per-layer all-reduces under tp).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu._private.jax_compat import install as _jax_compat
from ray_tpu.models import llama
from ray_tpu.parallel.sharding import logical_sharding, param_shardings

_jax_compat()


def model_module(cfg: llama.LlamaConfig):
    """Model family for a config: moe for MoEConfig (a LlamaConfig
    subclass, so it must be checked first), llama otherwise.  Keeps the
    train helpers honest — an MoE config must never silently build a
    dense model."""
    from ray_tpu.models import moe

    if isinstance(cfg, moe.MoEConfig):
        return moe
    return llama


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, total_steps: int = 10000,
                      b1: float = 0.9, b2: float = 0.95,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    """AdamW + cosine schedule + global-norm clip (the Llama pretrain recipe)."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), end_value=lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def create_train_state(key: jax.Array, cfg: llama.LlamaConfig,
                       optimizer: optax.GradientTransformation) -> TrainState:
    params = model_module(cfg).init_params(key, cfg)
    return TrainState(params=params, opt_state=optimizer.init(params),
                      step=jnp.zeros((), jnp.int32))


def make_train_step(cfg: llama.LlamaConfig,
                    optimizer: optax.GradientTransformation,
                    loss_fn: Callable | None = None) -> Callable:
    """Returns step(state, batch) -> (state, metrics). Pure; jit outside."""
    loss_fn = loss_fn or model_module(cfg).loss_fn

    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        def compute_loss(params):
            return loss_fn(params, batch, cfg)

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params=params, opt_state=opt_state,
                               step=state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm,
                           "step": new_state.step}

    return step


# ------------------------------------------------------- sharded wrappers
def _rules_for(mesh: Mesh) -> dict | None:
    """Sharding rules for a mesh: on a stage-bearing (pipeline) mesh the
    stacked "layers" param axis shards over "stage", so each stage holds
    its contiguous layer block and pipelined_loss_fn's per-stage reshape
    moves no data.  None = the default LOGICAL_RULES."""
    if mesh.shape.get("stage", 1) > 1:
        from ray_tpu.parallel.sharding import LOGICAL_RULES

        return {**LOGICAL_RULES, "layers": "stage"}
    return None


def state_shardings(cfg: llama.LlamaConfig, mesh: Mesh,
                    optimizer: optax.GradientTransformation):
    """NamedShardings for a TrainState: params follow the logical-axes
    table; optimizer-state leaves mirror whichever param they track
    (matched by shape), scalars replicate."""
    model = model_module(cfg)
    axes = model.param_logical_axes(cfg)
    p_sh = param_shardings(axes, mesh, rules=_rules_for(mesh))

    params_shape = jax.eval_shape(
        lambda k: model.init_params(k, cfg), jax.random.PRNGKey(0))
    shape_to_sh = {}
    for (path_a, leaf), (path_b, sh) in zip(
            jax.tree_util.tree_leaves_with_path(params_shape),
            jax.tree_util.tree_leaves_with_path(p_sh)):
        shape_to_sh[leaf.shape] = sh
    replicated = NamedSharding(mesh, P())

    def opt_leaf_sharding(leaf):
        return shape_to_sh.get(leaf.shape, replicated)

    opt_shape = jax.eval_shape(
        lambda: optimizer.init(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params_shape)))
    o_sh = jax.tree.map(opt_leaf_sharding, opt_shape)
    return TrainState(params=p_sh, opt_state=o_sh, step=replicated)


def batch_shardings(mesh: Mesh):
    """One sharding for every batch leaf ([b, s] token arrays) — used as a
    jit prefix pytree, so any batch dict layout works."""
    return logical_sharding(mesh, ("batch", "seq"))


def sharded_init(key: jax.Array, cfg: llama.LlamaConfig,
                 optimizer: optax.GradientTransformation,
                 mesh: Mesh) -> TrainState:
    """Initialize params directly into their sharded layout (no host-side
    full copy: jit with out_shardings materializes each shard on-device)."""
    st_sh = state_shardings(cfg, mesh, optimizer)
    with jax.set_mesh(mesh):
        init = jax.jit(
            functools.partial(create_train_state, cfg=cfg,
                              optimizer=optimizer),
            out_shardings=st_sh)
        return init(key)


def reshard_state(state, cfg: llama.LlamaConfig,
                  optimizer: optax.GradientTransformation,
                  mesh: Mesh):
    """Re-lay a TrainState pytree (host arrays from a checkpoint, or
    arrays sharded for a DIFFERENT mesh) onto `mesh` via the logical-axis
    rules — the elastic resume hook (ISSUE 8): after a membership-epoch
    world-size change the physical mesh changed but the logical table
    didn't, so a device_put of every leaf to its new NamedSharding is the
    whole resharding story.  Deterministic: same checkpoint + same mesh
    => bit-identical device state regardless of the world size it was
    saved under."""
    st_sh = state_shardings(cfg, mesh, optimizer)
    return jax.tree.map(
        lambda x, s: jax.device_put(jnp.asarray(x), s), state, st_sh)


def sharded_train_step(cfg: llama.LlamaConfig,
                       optimizer: optax.GradientTransformation,
                       mesh: Mesh, loss_fn: Callable | None = None,
                       n_micro: int | None = None):
    """Jitted step with explicit state/batch shardings; donates the state
    (params update in place in HBM).  On a stage-bearing mesh the trunk
    runs the GPipe pipeline (llama.pipelined_loss_fn) automatically."""
    if loss_fn is None and mesh.shape.get("stage", 1) > 1:
        # fsdp/tensor/data compose with the pipeline (only "stage" is
        # manual inside pipeline_apply; GSPMD shards the in-stage compute
        # over the auto axes).  seq (ring attention nests its own
        # shard_map) and expert (no pipelined MoE trunk) do not yet.
        unsupported = [a for a in ("seq", "expert")
                       if mesh.shape.get(a, 1) > 1]
        if unsupported:
            raise NotImplementedError(
                f"pipeline meshes compose with data/fsdp/tensor; axes "
                f"{unsupported} > 1 are not supported inside the "
                "pipelined trunk yet")

        def loss_fn(params, batch, cfg_, _mesh=mesh, _nm=n_micro):
            pl = getattr(model_module(cfg_), "pipelined_loss_fn", None)
            if pl is None:
                raise NotImplementedError(
                    f"{model_module(cfg_).__name__} has no pipelined "
                    "trunk; pipeline meshes (stage>1) currently support "
                    "the llama family")
            return pl(params, batch, cfg_, _mesh, _nm)
    st_sh = state_shardings(cfg, mesh, optimizer)
    b_sh = batch_shardings(mesh)
    step = make_train_step(cfg, optimizer, loss_fn)
    return jax.jit(step, in_shardings=(st_sh, b_sh),
                   out_shardings=(st_sh, None), donate_argnums=(0,))
