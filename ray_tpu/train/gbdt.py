"""XGBoost / LightGBM trainers: distributed GBDT over the worker group.

Analog of ray: python/ray/train/xgboost/xgboost_trainer.py and
lightgbm/lightgbm_trainer.py (both thin layers over the data-parallel
trainer: shard the dataset across workers, run the library's own
collective-aware training inside each, report metrics/checkpoints).

This environment ships neither xgboost nor lightgbm (and nothing may be
installed), so the library call is GATED: the trainer builds the full
data-parallel plumbing (worker group, shards, report loop) and raises a
clear ImportError from the workers only when the library itself is
absent.  With the library present the loop is the reference's shape:
rank 0 is authoritative, every rank trains on its shard.
"""
from __future__ import annotations

from typing import Any, Callable

from ray_tpu.train.trainer import DataParallelTrainer


def _make_gbdt_loop(lib_name: str, params: dict, dmatrix_kwargs: dict,
                    num_boost_round: int, label_column: str) -> Callable:
    def train_loop(config: dict) -> None:
        from ray_tpu.train import session

        try:
            if lib_name == "xgboost":
                import xgboost as lib
            else:
                import lightgbm as lib
        except ImportError as e:
            raise ImportError(
                f"{lib_name} is not installed; {lib_name.title()}Trainer "
                "needs it on every worker (offline env: provide a local "
                'wheel via runtime_env {"pip": {...}})') from e
        shard = session.get_dataset_shard("train")
        import numpy as np

        batches = list(shard.iter_batches(batch_size=None)) if shard \
            else []
        if not batches:
            session.report({"error": "empty shard"})
            return
        X = np.concatenate(
            [np.column_stack([b[k] for k in sorted(b) if k != label_column])
             for b in batches])
        y = np.concatenate([b[label_column] for b in batches])
        if lib_name == "xgboost":
            dtrain = lib.DMatrix(X, label=y, **dmatrix_kwargs)
            evals_result: dict = {}
            booster = lib.train(params, dtrain,
                                num_boost_round=num_boost_round,
                                evals=[(dtrain, "train")],
                                evals_result=evals_result)
            metric = {k: v[-1] for k, v in
                      evals_result.get("train", {}).items()}
        else:
            dtrain = lib.Dataset(X, label=y)
            booster = lib.train(params, dtrain,
                                num_boost_round=num_boost_round)
            metric = {}
        import tempfile

        ckpt_dir = tempfile.mkdtemp(prefix=f"{lib_name}_ckpt_")
        booster.save_model(f"{ckpt_dir}/model.{lib_name}")
        from ray_tpu.train.checkpoint import Checkpoint

        session.report({"boost_rounds": num_boost_round, **metric},
                       checkpoint=Checkpoint.from_directory(ckpt_dir))

    return train_loop


class XGBoostTrainer(DataParallelTrainer):
    """ray: XGBoostTrainer(params=..., label_column=..., datasets=...)."""

    def __init__(self, *, params: dict | None = None,
                 label_column: str = "label",
                 num_boost_round: int = 10,
                 dmatrix_kwargs: dict | None = None,
                 **kwargs: Any):
        super().__init__(
            _make_gbdt_loop("xgboost", params or {}, dmatrix_kwargs or {},
                            num_boost_round, label_column), **kwargs)


class LightGBMTrainer(DataParallelTrainer):
    """ray: LightGBMTrainer — same surface, lightgbm backend."""

    def __init__(self, *, params: dict | None = None,
                 label_column: str = "label",
                 num_boost_round: int = 10,
                 **kwargs: Any):
        super().__init__(
            _make_gbdt_loop("lightgbm", params or {}, {},
                            num_boost_round, label_column), **kwargs)
