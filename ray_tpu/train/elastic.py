"""Elastic gang training: membership epochs over a surviving worker gang.

ISSUE 8 / ROADMAP item 2.  The legacy recovery unit is the whole group —
any rank failure sends BackendExecutor through a full teardown + respawn
(_restart), re-paying every worker spawn and compile.  This module makes
membership a first-class, *versioned* property of the run instead:

- The driver owns a monotonically increasing **epoch** naming the current
  gang roster.  Epoch e's host collective group is
  ``train_host:<trial>:<e>`` — a fresh rendezvous per roster, so a stale
  incarnation can never satisfy (or wedge) the next one.
- **Shrink**: when a rank is lost (actor death, node death, a collective
  deadline naming it), survivors PARK at an epoch barrier
  (``TrainWorker.park_at_barrier`` stops the train fn at its next
  session touchpoint), the driver destroys the stale group — draining
  any rank still parked inside a collective with the dead peer — then
  re-forms the group at the new world size, re-runs the backend's
  per-gang bring-up (jax.distributed at the new world), and relaunches
  the train fn on the SURVIVING PROCESSES from the newest async
  checkpoint.  No process restart: imports, jit caches and the warmed
  arena are kept, so shrink MTTR is barrier + relaunch, not spawn +
  compile.
- **Regrow**: the dead slot's PG bundle is released eagerly (honest free
  capacity) and the controller's bundle scheduler re-reserves it as soon
  as the autoscaler (or a replacement in-process node) supplies
  capacity.  The driver then spawns a replacement worker on the
  re-reserved bundle WHILE the survivors keep training, and only the
  final roster flip interrupts them: at the next epoch boundary the
  joiner starts with ``session.joined=True`` and NO checkpoint — it
  receives current parameters from rank 0 via the collective broadcast
  (``train.host_broadcast``), so regrow works even when the replacement
  host does not share the checkpoint filesystem.

Elastic train fns opt into two session contracts (both no-ops for plain
fns on the legacy path): resume state from ``train.get_checkpoint()``
when present, and pass the initial state through
``train.host_broadcast`` so a joined rank bootstraps from rank 0.

Kill switch ``RAY_TPU_ELASTIC=0`` restores the restart-only loop
(same-run A/B); ``RAY_TPU_ELASTIC_REGROW=0`` keeps shrink but never
grows back.  Failpoint sites: ``train.epoch_barrier`` (a survivor
parking), ``train.rank_join`` (a joiner mid-parameter-broadcast).
"""
from __future__ import annotations

import logging
import os
import time
from typing import Callable

import ray_tpu
from ray_tpu import collective as col
from ray_tpu import tracing
from ray_tpu.train import backend_executor as _be

logger = logging.getLogger(__name__)

_TRUTHY = ("1", "true", "yes", "on")


def elastic_enabled() -> bool:
    """RAY_TPU_ELASTIC=0 restores the legacy restart loop (read at run
    start, so one process can A/B both paths)."""
    return os.environ.get("RAY_TPU_ELASTIC", "1").lower() in _TRUTHY


def regrow_enabled() -> bool:
    return os.environ.get(
        "RAY_TPU_ELASTIC_REGROW", "1").lower() in _TRUTHY


def epoch_group_name(trial_name: str, epoch: int) -> str:
    return f"train_host:{trial_name}:{epoch}"


class ElasticRun:
    """One elastic training run: drives the executor's WorkerGroup
    through membership epochs.  Created per BackendExecutor.run call;
    `stats` carries the transition log and MTTR rows the bench reads."""

    def __init__(self, executor: "_be.BackendExecutor"):
        self.exec = executor
        self.wg = executor.worker_group
        self.trial = executor.trial_name
        self.epoch = 0
        # Roster: PG-slot id per rank, in rank order.  Slot i owns PG
        # bundle i forever; ranks are re-assigned contiguously at every
        # epoch (survivors keep relative order, joiners append).
        self.active: list[int] = list(range(self.wg.num_workers))
        self._lost: set[int] = set()
        self._group_name: str | None = None
        self._stopping = False
        # Per-epoch dataset shard iterators: the DRIVER's handles own
        # the streaming_split coordinator actors — dropping them
        # mid-epoch kills every worker's shard with "handle out of
        # scope" (the legacy path keeps them alive in _run_once's
        # frame; this run object is the elastic equivalent).
        self._shards: list | None = None
        # ("shrink"|"regrow", t0): an MTTR clock started at failure
        # detection / roster flip, stamped into stats once the new
        # epoch's fns are relaunched.
        self._mttr_t0: tuple | None = None
        self.stats: dict = {"transitions": [], "epochs": 0}

    # ---------------------------------------------------------------- api
    def run(self, train_fn: Callable, config: dict, on_report,
            resume_checkpoint, latest_checkpoint) -> list:
        max_failures = self.exec.failure.max_failures
        failures = 0

        def newest():
            if latest_checkpoint is not None:
                return latest_checkpoint() or resume_checkpoint
            return resume_checkpoint

        def fail(exc: Exception) -> None:
            """One involuntary transition burns one max_failures round;
            budget exhausted raises `exc` itself."""
            nonlocal failures
            failures += 1
            self.exec._num_failures = failures
            if 0 <= max_failures < failures:
                raise exc from None

        pending: tuple | None = (resume_checkpoint, frozenset())
        while True:
            if pending is not None:
                ckpt, joined = pending
                try:
                    self._launch(train_fn, config, ckpt,
                                 joined_slots=joined)
                    pending = None
                    if self._mttr_t0 is not None:
                        # MTTR clock stops only once the fns are
                        # RELAUNCHED (start refs resolved), not at
                        # roster re-form.
                        key, t0 = self._mttr_t0
                        self._mttr_t0 = None
                        self.stats[f"elastic_{key}_mttr_ms"] = round(
                            (time.perf_counter() - t0) * 1e3, 1)
                except Exception as e:  # noqa: BLE001 - epoch bring-up
                    # A rank can die DURING the launch (e.g. a joiner
                    # crashing in its bootstrap broadcast before the
                    # start reply lands): classify survivors and
                    # shrink, exactly like a mid-epoch death — full
                    # restart only when nobody answers the barrier.
                    logger.warning("epoch %d launch failed: %r",
                                   self.epoch, e)
                    fail(_be.TrainingFailedError(
                        f"epoch {self.epoch} launch failed: {e!r}"))
                    survivors = self._transition(self.active)
                    if survivors:
                        try:
                            self._reform(survivors, kind="shrink")
                            pending = (newest(), frozenset())
                            continue
                        except Exception as e2:  # noqa: BLE001
                            logger.warning("epoch re-form failed: %r",
                                           e2)
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
            kind, payload, err = self._poll(on_report)
            if kind == "done":
                return payload
            if kind == "fn_error":
                # Same failure-budget contract as the legacy loop: a
                # train-fn error burns one max_failures round, then the
                # LIVE gang retries at the next epoch from the newest
                # checkpoint (all workers answered get_status to get
                # here — no respawn needed).
                fail(_be.TrainingFailedError(payload))
                survivors = self._transition(self.active)
                if not survivors:
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
                try:
                    self._reform(survivors, kind="retry")
                except Exception as e:  # noqa: BLE001
                    logger.warning("retry re-form failed: %r", e)
                    self._full_restart()
                pending = (newest(), frozenset())
                continue
            if kind == "dead":
                fail(_be.TrainingFailedError(
                    f"rank lost at epoch {self.epoch}: {err!r}"))
                t0 = time.perf_counter()
                for slot in payload:
                    self._remove_slot(slot)
                survivors = self._transition(
                    [s for s in self.active if s not in payload])
                if not survivors:
                    logger.warning(
                        "no survivors at epoch %d: full restart",
                        self.epoch)
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
                try:
                    self._reform(survivors, kind="shrink")
                except Exception as e:  # noqa: BLE001 - backend re-init
                    logger.warning("epoch re-form failed: %r", e)
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
                pending = (newest(), frozenset())
                self._mttr_t0 = ("shrink", t0)
            elif kind == "regrow":
                joiners = payload
                t0 = time.perf_counter()
                survivors = self._transition(self.active)
                if not survivors:
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
                roster = survivors + [s for s in joiners
                                      if s not in survivors]
                self._lost -= set(joiners)
                try:
                    self._reform(roster, kind="regrow")
                except Exception as e:  # noqa: BLE001
                    logger.warning("regrow re-form failed: %r", e)
                    self._full_restart()
                    pending = (newest(), frozenset())
                    continue
                pending = (newest(), frozenset(joiners))
                self._mttr_t0 = ("regrow", t0)

    # ------------------------------------------------------------- launch
    def _launch(self, train_fn, config, resume_checkpoint,
                joined_slots=frozenset()) -> None:
        wg = self.wg
        roster = list(self.active)
        n = len(roster)
        workers = [wg.workers[s] for s in roster]
        node_ids = ray_tpu.get(
            [w.get_node_id.remote() for w in workers], timeout=60.0)
        seen: dict[str, int] = {}
        local_ranks = []
        for nid in node_ids:
            local_ranks.append(seen.get(nid, 0))
            seen[nid] = local_ranks[-1] + 1
        self.exec.backend.on_training_start(wg)
        self._group_name = epoch_group_name(self.trial, self.epoch) \
            if n >= 2 else None
        # Keep the executor's shutdown pointed at the CURRENT epoch's
        # group (each stale epoch's group is destroyed at its own
        # transition; the last one falls to shutdown).
        self.exec._host_group = self._group_name or \
            f"train_host:{self.trial}"
        if self._group_name is not None:
            col.create_collective_group(workers, n, list(range(n)),
                                        group_name=self._group_name)
        shards, config = _be._dataset_shards(config, n)
        self._shards = shards
        ray_tpu.get([
            w.start_train_fn.remote(
                train_fn, config, world_rank=r, world_size=n,
                local_rank=local_ranks[r], trial_name=self.trial,
                checkpoint=None if roster[r] in joined_slots
                else resume_checkpoint,
                dataset_shards=shards[r], host_group=self._group_name,
                epoch=self.epoch, joined=roster[r] in joined_slots)
            for r, w in enumerate(workers)
        ], timeout=120.0)
        self.stats["epochs"] = self.epoch
        self.stats.setdefault("world_by_epoch", {})[self.epoch] = n

    # --------------------------------------------------------------- poll
    def _flush_pending(self, pending: list, on_report) -> None:
        """Deliver reports still buffered for lock-step alignment before
        a transition return: their checkpoints must reach the manager
        (a fresher resume point, and trainer-side ephemeral-checkpoint
        cleanup) instead of being silently dropped.  Stop verdicts only
        flag _stopping — the roster is about to be interrupted anyway."""
        while any(pending):
            round_msgs = [p.pop(0) if p else None for p in pending]
            if on_report is not None:
                verdict = on_report(
                    [m for m in round_msgs if m is not None])
                if verdict == "stop":
                    self._stopping = True

    def _poll(self, on_report) -> tuple:
        """Drain report streams in lock-step (legacy semantics) with two
        elastic differences: a per-rank failure names the LOST SLOT
        instead of failing the run, and a ~1 Hz side-poll spawns
        replacement workers as soon as released bundles re-reserve."""
        wg = self.wg
        roster = list(self.active)
        n = len(roster)
        done = [False] * n
        pending: list[list] = [[] for _ in range(n)]
        next_regrow = 0.0
        while not all(done):
            progressed = False
            for r, slot in enumerate(roster):
                if done[r] or pending[r]:
                    continue
                try:
                    msg = ray_tpu.get(
                        wg.workers[slot].next_result.remote(timeout=1.0),
                        timeout=60.0)
                except Exception as e:  # noqa: BLE001 - rank lost
                    self._flush_pending(pending, on_report)
                    return ("dead", [slot], e)
                if msg is None:
                    continue
                progressed = True
                if msg["type"] == "done":
                    done[r] = True
                elif msg["type"] == "report":
                    pending[r].append(msg)
            if all(p or done[i] for i, p in enumerate(pending)) and \
                    any(pending):
                round_msgs = [p.pop(0) if p else None for p in pending]
                if on_report is not None:
                    verdict = on_report(
                        [m for m in round_msgs if m is not None])
                    if verdict == "stop":
                        self._stopping = True
                        wg.execute("stop")
            now = time.monotonic()
            if (self._lost and not self._stopping and regrow_enabled()
                    and now >= next_regrow):
                next_regrow = now + 1.0
                joiners = self._try_regrow()
                if joiners:
                    self._flush_pending(pending, on_report)
                    return ("regrow", joiners, None)
            if not progressed:
                time.sleep(0.05)
        statuses = []
        for r, slot in enumerate(roster):
            try:
                statuses.append(ray_tpu.get(
                    wg.workers[slot].get_status.remote(), timeout=30.0))
            except Exception as e:  # noqa: BLE001 - died while finishing
                return ("dead", [slot], e)
        errors = [(r, s["error"]) for r, s in enumerate(statuses)
                  if s["error"]]
        if errors:
            rank, tb = errors[0]
            return ("fn_error",
                    f"train fn failed on rank {rank} "
                    f"(epoch {self.epoch}):\n{tb}", None)
        results = [ray_tpu.get(wg.workers[slot].get_result.remote(),
                               timeout=30.0) for slot in roster]
        return ("done", results, None)

    # ------------------------------------------------------------- regrow
    def _try_regrow(self) -> list[int] | None:
        """Non-disruptive regrow prep: once the PG reports CREATED again
        (every released bundle re-reserved), spawn replacement workers
        on the lost slots.  Survivors keep training throughout — only
        the roster flip after this returns interrupts them."""
        try:
            if self.wg.pg_state() != "CREATED":
                return None
        except Exception:  # noqa: BLE001 - controller hiccup: retry
            return None
        joiners = []
        for slot in sorted(self._lost):
            w = self.wg.restore_worker(slot)
            try:
                ray_tpu.get(w.get_node_id.remote(), timeout=60.0)
            except Exception as e:  # noqa: BLE001 - capacity raced away
                logger.warning("regrow probe on slot %d failed: %r",
                               slot, e)
                self.wg.remove_worker(slot)
                try:
                    self.wg.reschedule_lost_bundles()
                except Exception:  # noqa: BLE001
                    pass
                # Partial regrow: slots already restored this tick must
                # join NOW — their live actors would trip
                # restore_worker's occupied-slot assert on the next
                # tick; the failed slot retries at a later epoch.
                break
            joiners.append(slot)
        return joiners or None

    # -------------------------------------------------------- transitions
    def _remove_slot(self, slot: int) -> None:
        """Eagerly drop a lost slot: kill the corpse, release its PG
        bundle, ask the scheduler to start re-filling the hole, and
        post an autoscaler demand floor for the full gang."""
        self.wg.remove_worker(slot)
        self._lost.add(slot)
        try:
            self.wg.reschedule_lost_bundles()
        except Exception:  # noqa: BLE001 - controller transient
            pass
        self._post_autoscaler_demand()

    def _transition(self, roster_slots: list[int]) -> list[int]:
        """Epoch barrier: park every candidate survivor, destroy the
        stale collective group (draining ranks parked inside a
        collective with the dead peer), and join each train-fn thread.
        Returns the slots that actually parked; the rest are removed."""
        wg = self.wg
        park = [(s, wg.workers[s].park_at_barrier.remote(self.epoch))
                for s in roster_slots if wg.workers[s] is not None]
        if self._group_name is not None:
            col.destroy_collective_group(
                self._group_name,
                reason=f"membership epoch {self.epoch} of trial "
                       f"{self.trial!r} ended (elastic transition)")
        survivors = []
        for s, ref in park:
            try:
                ray_tpu.get(ref, timeout=30.0)
                st = ray_tpu.get(
                    wg.workers[s].join_train.remote(timeout=20.0),
                    timeout=40.0)
                if st["parked"]:
                    survivors.append(s)
                    continue
                logger.warning("slot %d wedged at the epoch barrier; "
                               "treating as lost", s)
            except Exception as e:  # noqa: BLE001 - died at the barrier
                logger.warning("slot %d lost at the epoch barrier: %r",
                               s, e)
            self._remove_slot(s)
        return survivors

    def _reform(self, roster: list[int], kind: str) -> None:
        # Flight recorder: one span per membership transition (the MTTR
        # anatomy — group destroy, backend re-init — lands on the same
        # timeline as the collectives it unblocks).
        with tracing.span(f"elastic.{kind}",
                          attrs={"world": len(roster),
                                 "trial": self.trial}) as sp:
            self.epoch += 1
            sp["epoch"] = self.epoch
            self.active = roster
            workers = [self.wg.workers[s] for s in roster]
            self.exec.backend.on_epoch_start(workers, self.epoch)
            self._post_autoscaler_demand()
        self.stats["transitions"].append(
            {"epoch": self.epoch, "kind": kind, "world": len(roster)})
        logger.warning("membership epoch %d (%s): world_size=%d "
                       "slots=%s", self.epoch, kind, len(roster), roster)

    def _full_restart(self) -> None:
        """Fallback when elastic has nothing to salvage (no survivors,
        or epoch bring-up failed): the legacy teardown + respawn, folded
        into the epoch sequence as a fresh full roster."""
        # A transition degraded to a respawn must not stamp an
        # elastic_* MTTR row — the legacy restart_mttr_ms covers it.
        self._mttr_t0 = None
        self.exec._restart()
        self.wg = self.exec.worker_group
        self.epoch += 1
        self.active = list(range(self.wg.num_workers))
        self._lost = set()
        self._group_name = None
        self.stats["transitions"].append(
            {"epoch": self.epoch, "kind": "restart",
             "world": len(self.active)})

    def _post_autoscaler_demand(self) -> None:
        """While shrunk, pin an autoscaler demand floor for the FULL
        gang (the regrow path's capacity request); clear it once whole
        again.  Best-effort — no autoscaler, no harm."""
        try:
            from ray_tpu.autoscaler import request_resources

            bundles = self.exec.scaling.bundles() if self._lost else []
            request_resources(bundles=bundles, requester="elastic")
        except Exception:  # noqa: BLE001
            pass
