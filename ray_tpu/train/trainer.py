"""Trainers: the `Trainer(...).fit() -> Result` public surface.

Analog of ray: python/ray/train/base_trainer.py:567 (fit), data_parallel_
trainer.py (DataParallelTrainer), torch/torch_trainer.py.  The TPU-native
flagship is `JaxTrainer`: gang-places one jax process per host, runs the
multi-host rendezvous (backend.py), and the user loop shards with
pjit/shard_map — per-step collectives are compiled, not RPCs.

fit() here drives the BackendExecutor directly; when a Tuner wraps a
trainer (`tune.Tuner(trainer)`), `as_trainable()` exposes the same run as
a Tune trainable (ray: BaseTrainer.fit wraps itself in a 1-trial Tuner —
we invert the layering so Train has no hard Tune dependency).
"""
from __future__ import annotations

import os
import tempfile
from typing import Any, Callable

from ray_tpu.train.backend import Backend, JaxBackend
from ray_tpu.train.backend_executor import (BackendExecutor,
                                            TrainingFailedError)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)


class Result:
    """ray: ray.train.Result — final metrics + best/last checkpoint."""

    def __init__(self, metrics: dict | None, checkpoint: Checkpoint | None,
                 error: Exception | None = None,
                 metrics_history: list[dict] | None = None,
                 path: str | None = None):
        self.metrics = metrics
        self.checkpoint = checkpoint
        self.error = error
        self.metrics_history = metrics_history or []
        self.path = path

    def __repr__(self):
        return (f"Result(metrics={self.metrics}, "
                f"checkpoint={self.checkpoint}, error={self.error})")


class BaseTrainer:
    _backend_cls: type[Backend] = JaxBackend

    def __init__(self, train_loop_per_worker: Callable | None = None,
                 *, train_loop_config: dict | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 resume_from_checkpoint: Checkpoint | None = None,
                 datasets: dict | None = None,
                 dataset_config=None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}
        self.dataset_config = dataset_config

    # ------------------------------------------------------------ plumbing
    def _storage_path(self) -> str:
        base = self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")
        name = self.run_config.name or "train"
        return os.path.join(base, name)

    def fit(self) -> Result:
        executor = BackendExecutor(
            self.scaling_config, self._backend_cls(),
            self.run_config.failure_config or FailureConfig(),
            trial_name=self.run_config.name or "train")
        storage = self._storage_path()
        manager = CheckpointManager(
            storage,
            self.run_config.checkpoint_config or CheckpointConfig())
        history: list[dict] = []
        last_metrics: dict | None = None
        stop_criteria = self.run_config.stop or {}

        def on_report(round_msgs: list[dict]):
            nonlocal last_metrics
            # rank-0 metrics are authoritative (ray: only rank-0 results
            # propagate to Tune); any rank may attach the checkpoint.
            by_rank = {m["rank"]: m for m in round_msgs}
            rank0 = by_rank.get(0) or round_msgs[0]
            last_metrics = rank0["metrics"]
            history.append(last_metrics)
            ckpt = next((m["checkpoint"] for m in round_msgs
                         if m.get("checkpoint")), None)
            if ckpt is not None:
                manager.register(ckpt, last_metrics)
            # Only one checkpoint per round is kept; other ranks'
            # EPHEMERAL ones (temp handoff dirs, Checkpoint.mark_
            # ephemeral) would otherwise leak under /tmp forever.
            import shutil

            for m in round_msgs:
                c = m.get("checkpoint")
                if c is not None and c is not ckpt and c.is_ephemeral():
                    shutil.rmtree(c.path, ignore_errors=True)
            for key, bound in stop_criteria.items():
                v = last_metrics.get(key)
                if v is not None and v >= bound:
                    return "stop"
            return None

        executor.start()
        # Bound before the try: the finally block below reads it, and a
        # non-TrainingFailedError escaping executor.run would otherwise
        # leave it unbound there.
        error = None
        try:
            self._pre_run(executor)
            executor.run(self._train_fn(), self.train_loop_config,
                         on_report=on_report,
                         resume_checkpoint=self.resume_from_checkpoint,
                         latest_checkpoint=lambda:
                         manager.latest_checkpoint)
        except TrainingFailedError as e:
            error = e
        finally:
            # Driver-side async checkpoint writes (from_pytree_async in
            # callbacks, tests) must not outlive the run.  A failed
            # write surfaces on the Result, never as an exception out of
            # the finally block — that would mask the training error AND
            # skip executor.shutdown() (leaking the worker group and the
            # host collective's rendezvous).
            try:
                from ray_tpu.train import checkpoint as ckpt_mod

                ckpt_mod.flush_pending_writes()
            except Exception as e:  # noqa: BLE001
                error = error or TrainingFailedError(
                    f"async checkpoint write failed: {e!r}")
            executor.shutdown()
        return Result(metrics=last_metrics,
                      checkpoint=manager.latest_checkpoint,
                      error=error, metrics_history=history, path=storage)

    def _train_fn(self) -> Callable:
        if self.train_loop_per_worker is None:
            raise ValueError("train_loop_per_worker is required")
        return self.train_loop_per_worker

    def _pre_run(self, executor: BackendExecutor) -> None:
        """Hook: e.g. attach dataset shards before training starts."""
        if not self.datasets:
            return
        # Each worker's session.config gains an iterator over its shard
        # via ray_tpu.data streaming_split at run time (data lib).
        self.train_loop_config.setdefault("_datasets", self.datasets)
        if self.dataset_config is not None:
            self.train_loop_config.setdefault(
                "_datasets_to_split", self.dataset_config.datasets_to_split)

    # --------------------------------------------------------------- tune
    def as_trainable(self) -> Callable:
        """A Tune-compatible function trainable closing over this trainer
        (ray: BaseTrainer.as_trainable base_trainer.py:819)."""
        trainer = self

        def trainable(config: dict):
            from ray_tpu import tune

            merged = dict(trainer.train_loop_config)
            merged.update(config.get("train_loop_config", config))
            t = type(trainer)(
                trainer.train_loop_per_worker,
                train_loop_config=merged,
                scaling_config=trainer.scaling_config,
                run_config=trainer.run_config,
                datasets=trainer.datasets)
            result = t.fit()
            if result.error:
                raise result.error
            final = dict(result.metrics or {})
            tune.report(final, checkpoint=result.checkpoint)
            return final

        return trainable


class DataParallelTrainer(BaseTrainer):
    """SPMD data-parallel training (ray: DataParallelTrainer): same fn on
    every worker; model replication/sharding is the step's mesh layout."""


class JaxTrainer(DataParallelTrainer):
    """Flagship TPU trainer: one process per host, jax.distributed
    rendezvous, user loop uses ray_tpu.train.step helpers with a global
    mesh (analog of ray: TorchTrainer + TorchXLAConfig torch/xla/config.py:20,
    re-designed: no xmp spawn — jax owns all local chips per process)."""

    _backend_cls = JaxBackend
