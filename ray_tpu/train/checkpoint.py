"""Checkpoint: a directory handle + jax pytree (de)serialization.

Analog of ray: python/ray/train/_checkpoint.py:56 (Checkpoint = dir on a
pyarrow.fs) + train/_internal/checkpoint_manager.py (bounded, scored).
TPU-native additions: `from_pytree`/`to_pytree` write sharded jax arrays
via orbax (async-capable, resumable at 8B+ scale, SURVEY §7 "straggler-
free checkpointing"); plain numpy fallback keeps tests hermetic.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
from typing import Any

# ---------------------------------------------------------- async writer
# One background writer thread per process (ISSUE 5 satellite): the train
# step loop hands a flattened pytree to `from_pytree_async` and keeps
# computing while serialization+write run here; the write is forced
# complete by Checkpoint.wait(), by CheckpointManager.register(), by
# pickling the handle (it never crosses a process boundary half-written),
# and by flush_pending_writes() at fit()/train-fn exit.
_writer_lock = threading.Lock()
_writer_pool = None
# STRONG refs to in-flight write futures: a handle dropped without ever
# reaching a flush point (an abandoned conditional save) must still be
# waited out — and a FAILED write must still surface — at fit()/train-fn
# exit.  Successful futures self-remove on completion; failed ones stay
# until a flush observes (and raises) them.
_inflight_futs: set = set()


def _writer():
    global _writer_pool
    with _writer_lock:
        if _writer_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _writer_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="raytpu-ckpt-writer")
        return _writer_pool


def _track(fut) -> None:
    _inflight_futs.add(fut)

    def _done(f):
        if not f.cancelled() and f.exception() is None:
            _inflight_futs.discard(f)
    fut.add_done_callback(_done)


def flush_pending_writes(timeout: float | None = None) -> int:
    """Block until every in-flight async checkpoint write in this
    process has completed; re-raises the first failure; returns how
    many were pending.  Called at fit() exit and when a train fn
    finishes, so no background save can outlive (or silently fail
    after) the run that started it."""
    pending = list(_inflight_futs)
    first_err = None
    for fut in pending:
        try:
            fut.result(timeout)
        except Exception as e:  # noqa: BLE001 - re-raised below
            first_err = first_err or e
        # Observed (success or failure): drop it either way so a failed
        # write doesn't poison every later run in this process.
        _inflight_futs.discard(fut)
    if first_err is not None:
        raise first_err
    return len(pending)


class Checkpoint:
    """An immutable directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        # Future of an in-flight background write (from_pytree_async);
        # None once complete.  Never crosses process boundaries — see
        # __reduce__.
        self._pending = None

    def wait(self, timeout: float | None = None) -> "Checkpoint":
        """Block until this checkpoint's background write (if any) has
        finished; re-raises a failed write's exception.  No-op for
        synchronously written checkpoints.  `_pending` clears only on a
        COMPLETED future — a timed-out wait must leave the handle
        flagged, or the next register()/pickle would silently treat a
        half-written directory as done (and a terminally failed write
        keeps re-raising on every later flush point)."""
        fut = self._pending
        if fut is not None:
            fut.result(timeout)
            self._pending = None
        return self

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="raytpu-ckpt-")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> dict:
        self.wait()
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    @classmethod
    def from_pytree(cls, tree: Any, path: str | None = None,
                    use_orbax: bool = True) -> "Checkpoint":
        """Persist a pytree of (possibly sharded) jax arrays.

        Orbax handles sharded arrays per-host (each host writes its own
        shards — no gather to host 0); numpy fallback for small trees.
        """
        d = path or tempfile.mkdtemp(prefix="raytpu-ckpt-")
        os.makedirs(d, exist_ok=True)
        if use_orbax:
            try:
                import orbax.checkpoint as ocp

                ckptr = ocp.StandardCheckpointer()
                ckptr.save(os.path.join(d, "state"), tree, force=True)
                ckptr.wait_until_finished()
                ckptr.close()
                return cls(d)
            except Exception:  # noqa: BLE001 - fall back to numpy
                pass
        import jax
        import numpy as np

        leaves, treedef = jax.tree.flatten(tree)
        np.savez(os.path.join(d, "state.npz"),
                 **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
        with open(os.path.join(d, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        return cls(d)

    @classmethod
    def from_pytree_async(cls, tree: Any, path: str | None = None,
                          use_orbax: bool = True) -> "Checkpoint":
        """`from_pytree` with serialization+write offloaded to the
        process's background writer thread, so checkpointing overlaps
        the next train steps instead of blocking the loop (ISSUE 5
        satellite).  Returns the Checkpoint handle immediately; the
        write is forced complete by wait(), by the next
        CheckpointManager.register(), by pickling the handle, and by
        flush_pending_writes() at fit() exit.

        The tree is flattened NOW (cheap, and it fails fast on
        non-pytrees); the leaves must not be mutated in place before
        the write lands — jax arrays are immutable, so in a jax train
        loop the contract is automatic."""
        import jax

        d = path or tempfile.mkdtemp(prefix="raytpu-ckpt-")
        os.makedirs(d, exist_ok=True)
        leaves, treedef = jax.tree.flatten(tree)

        def _write() -> None:
            cls.from_pytree(jax.tree.unflatten(treedef, leaves), path=d,
                            use_orbax=use_orbax)

        ckpt = cls(d)
        ckpt._pending = _writer().submit(_write)
        _track(ckpt._pending)
        return ckpt

    def to_pytree(self, target: Any = None) -> Any:
        """Restore; `target` (a pytree of like-shaped arrays or
        ShapeDtypeStructs with shardings) directs orbax restoration into
        the right layout."""
        self.wait()
        state_dir = os.path.join(self.path, "state")
        if os.path.isdir(state_dir):
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            try:
                return ckptr.restore(
                    state_dir, target) if target is not None \
                    else ckptr.restore(state_dir)
            finally:
                ckptr.close()
        import jax
        import numpy as np

        with open(os.path.join(self.path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        data = np.load(os.path.join(self.path, "state.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        return jax.tree.unflatten(treedef, leaves)

    EPHEMERAL_MARKER = ".raytpu-ephemeral"

    @classmethod
    def mark_ephemeral(cls, path: str) -> None:
        """Flag a checkpoint directory as a one-shot handoff: the first
        CheckpointManager.register() that copies it into run storage
        also deletes it.  Producers that write into a temp dir (e.g. the
        HF report callback) use this so per-save snapshots don't pile up
        under /tmp."""
        with open(os.path.join(path, cls.EPHEMERAL_MARKER), "w"):
            pass

    def is_ephemeral(self) -> bool:
        return os.path.exists(os.path.join(self.path,
                                           self.EPHEMERAL_MARKER))

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        # A handle must never cross a process boundary (train.report →
        # coordinator, actor replies) with its write still in flight:
        # the receiver reconstructs a plain path handle and would read a
        # half-written directory.  Pickling IS the synchronization
        # point.
        self.wait()
        return (Checkpoint, (self.path,))


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    """Registers reported checkpoints into the run's storage dir, keeps the
    best `num_to_keep` by score (ray: train/_internal/checkpoint_manager)."""

    def __init__(self, storage_path: str, config=None):
        from ray_tpu.train.config import CheckpointConfig

        self.config = config or CheckpointConfig()
        self.storage_path = storage_path
        os.makedirs(storage_path, exist_ok=True)
        self._checkpoints: list[_TrackedCheckpoint] = []
        self._index = 0

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        # Async-written checkpoints flush here: register() is the
        # explicit wait() point — the copy below must see a complete
        # directory.
        checkpoint.wait()
        dest = os.path.join(self.storage_path,
                            f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, dest)
            marker = os.path.join(dest, Checkpoint.EPHEMERAL_MARKER)
            if os.path.exists(marker):
                # Ephemeral handoff: consume (delete) the producer's
                # temp copy now that storage owns the data.
                os.unlink(marker)
                shutil.rmtree(checkpoint.path, ignore_errors=True)
        tracked = _TrackedCheckpoint(Checkpoint(dest), dict(metrics),
                                     self._index)
        self._index += 1
        self._checkpoints.append(tracked)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({"metrics": metrics, "ts": time.time()}, f)
        self._enforce_limit()
        return tracked.checkpoint

    def _score(self, t: _TrackedCheckpoint) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return t.index          # recency
        v = float(t.metrics.get(attr, float("-inf")))
        return v if self.config.checkpoint_score_order == "max" else -v

    def _enforce_limit(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        self._checkpoints.sort(key=self._score)
        while len(self._checkpoints) > k:
            victim = self._checkpoints.pop(0)
            shutil.rmtree(victim.checkpoint.path, ignore_errors=True)

    @property
    def latest_checkpoint(self) -> Checkpoint | None:
        """Most RECENT registration (ray: Result.checkpoint).  Explicit
        max over index: _enforce_limit re-sorts the list by SCORE when a
        checkpoint_score_attribute is set, so list order stops meaning
        recency — crash-restart resume (backend_executor.run) depends on
        this being the newest, not the best."""
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Checkpoint | None:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=self._score).checkpoint
