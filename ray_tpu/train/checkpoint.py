"""Checkpoint: a directory handle + jax pytree (de)serialization.

Analog of ray: python/ray/train/_checkpoint.py:56 (Checkpoint = dir on a
pyarrow.fs) + train/_internal/checkpoint_manager.py (bounded, scored).
TPU-native additions: `from_pytree`/`to_pytree` write sharded jax arrays
via orbax (async-capable, resumable at 8B+ scale, SURVEY §7 "straggler-
free checkpointing"); plain numpy fallback keeps tests hermetic.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any


class Checkpoint:
    """An immutable directory of checkpoint data."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: dict) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="raytpu-ckpt-")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> dict:
        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return pickle.load(f)

    @classmethod
    def from_pytree(cls, tree: Any, path: str | None = None,
                    use_orbax: bool = True) -> "Checkpoint":
        """Persist a pytree of (possibly sharded) jax arrays.

        Orbax handles sharded arrays per-host (each host writes its own
        shards — no gather to host 0); numpy fallback for small trees.
        """
        d = path or tempfile.mkdtemp(prefix="raytpu-ckpt-")
        os.makedirs(d, exist_ok=True)
        if use_orbax:
            try:
                import orbax.checkpoint as ocp

                ckptr = ocp.StandardCheckpointer()
                ckptr.save(os.path.join(d, "state"), tree, force=True)
                ckptr.wait_until_finished()
                ckptr.close()
                return cls(d)
            except Exception:  # noqa: BLE001 - fall back to numpy
                pass
        import jax
        import numpy as np

        leaves, treedef = jax.tree.flatten(tree)
        np.savez(os.path.join(d, "state.npz"),
                 **{str(i): np.asarray(x) for i, x in enumerate(leaves)})
        with open(os.path.join(d, "treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)
        return cls(d)

    def to_pytree(self, target: Any = None) -> Any:
        """Restore; `target` (a pytree of like-shaped arrays or
        ShapeDtypeStructs with shardings) directs orbax restoration into
        the right layout."""
        state_dir = os.path.join(self.path, "state")
        if os.path.isdir(state_dir):
            import orbax.checkpoint as ocp

            ckptr = ocp.StandardCheckpointer()
            try:
                return ckptr.restore(
                    state_dir, target) if target is not None \
                    else ckptr.restore(state_dir)
            finally:
                ckptr.close()
        import jax
        import numpy as np

        with open(os.path.join(self.path, "treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        data = np.load(os.path.join(self.path, "state.npz"))
        leaves = [data[str(i)] for i in range(len(data.files))]
        return jax.tree.unflatten(treedef, leaves)

    EPHEMERAL_MARKER = ".raytpu-ephemeral"

    @classmethod
    def mark_ephemeral(cls, path: str) -> None:
        """Flag a checkpoint directory as a one-shot handoff: the first
        CheckpointManager.register() that copies it into run storage
        also deletes it.  Producers that write into a temp dir (e.g. the
        HF report callback) use this so per-save snapshots don't pile up
        under /tmp."""
        with open(os.path.join(path, cls.EPHEMERAL_MARKER), "w"):
            pass

    def is_ephemeral(self) -> bool:
        return os.path.exists(os.path.join(self.path,
                                           self.EPHEMERAL_MARKER))

    def __repr__(self):
        return f"Checkpoint({self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: dict, index: int):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index


class CheckpointManager:
    """Registers reported checkpoints into the run's storage dir, keeps the
    best `num_to_keep` by score (ray: train/_internal/checkpoint_manager)."""

    def __init__(self, storage_path: str, config=None):
        from ray_tpu.train.config import CheckpointConfig

        self.config = config or CheckpointConfig()
        self.storage_path = storage_path
        os.makedirs(storage_path, exist_ok=True)
        self._checkpoints: list[_TrackedCheckpoint] = []
        self._index = 0

    def register(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        dest = os.path.join(self.storage_path,
                            f"checkpoint_{self._index:06d}")
        if os.path.abspath(checkpoint.path) != dest:
            if os.path.exists(dest):
                shutil.rmtree(dest)
            shutil.copytree(checkpoint.path, dest)
            marker = os.path.join(dest, Checkpoint.EPHEMERAL_MARKER)
            if os.path.exists(marker):
                # Ephemeral handoff: consume (delete) the producer's
                # temp copy now that storage owns the data.
                os.unlink(marker)
                shutil.rmtree(checkpoint.path, ignore_errors=True)
        tracked = _TrackedCheckpoint(Checkpoint(dest), dict(metrics),
                                     self._index)
        self._index += 1
        self._checkpoints.append(tracked)
        with open(os.path.join(dest, "metrics.json"), "w") as f:
            json.dump({"metrics": metrics, "ts": time.time()}, f)
        self._enforce_limit()
        return tracked.checkpoint

    def _score(self, t: _TrackedCheckpoint) -> float:
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            return t.index          # recency
        v = float(t.metrics.get(attr, float("-inf")))
        return v if self.config.checkpoint_score_order == "max" else -v

    def _enforce_limit(self) -> None:
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        self._checkpoints.sort(key=self._score)
        while len(self._checkpoints) > k:
            victim = self._checkpoints.pop(0)
            shutil.rmtree(victim.checkpoint.path, ignore_errors=True)

    @property
    def latest_checkpoint(self) -> Checkpoint | None:
        """Most RECENT registration (ray: Result.checkpoint).  Explicit
        max over index: _enforce_limit re-sorts the list by SCORE when a
        checkpoint_score_attribute is set, so list order stops meaning
        recency — crash-restart resume (backend_executor.run) depends on
        this being the newest, not the best."""
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda t: t.index).checkpoint

    @property
    def best_checkpoint(self) -> Checkpoint | None:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=self._score).checkpoint
