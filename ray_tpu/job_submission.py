"""Job submission: run driver entrypoints on the cluster, track status/logs.

Analog of ray: python/ray/dashboard/modules/job/ (JobManager
job_manager.py:57, job_supervisor.py driving `ray job submit` entrypoints,
SDK sdk.py JobSubmissionClient).  REST transport collapses to actor calls:
a detached `_JobManager` actor owns a `_JobSupervisor` actor per job, which
runs the entrypoint as a subprocess with RAY_TPU_ADDRESS exported so the
child driver attaches to this cluster.
"""
from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field

import ray_tpu

JOB_MANAGER_NAME = "_JOB_MANAGER"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobStatus:
    """Status namespace (ray: job_submission.JobStatus — a str enum;
    plain strings here, same values)."""
    PENDING = PENDING
    RUNNING = RUNNING
    SUCCEEDED = SUCCEEDED
    FAILED = FAILED
    STOPPED = STOPPED

    @staticmethod
    def is_terminal(status: str) -> bool:
        return status in (SUCCEEDED, FAILED, STOPPED)


class JobType:
    """ray: job_submission.JobType — only SUBMISSION exists here (the
    reference's DRIVER type tracks ad-hoc drivers in its job table)."""
    SUBMISSION = "SUBMISSION"
    DRIVER = "DRIVER"


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = PENDING
    start_time: float = 0.0
    end_time: float = 0.0
    return_code: int | None = None
    metadata: dict = field(default_factory=dict)


# ray: JobDetails is the REST-facing superset of JobInfo; the dict rows
# list_jobs returns carry the same fields, so the record type is shared.
JobDetails = JobInfo
DriverInfo = JobInfo


class _JobSupervisor:
    """One per job: runs the entrypoint subprocess and captures output
    (ray: job_supervisor.py)."""

    def __init__(self, job_id: str, entrypoint: str, controller_addr: str,
                 env: dict | None = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.status = PENDING
        self.return_code: int | None = None
        self.log = ""
        self._proc: subprocess.Popen | None = None
        self._thread = threading.Thread(
            target=self._run, args=(controller_addr, env or {}), daemon=True)
        self._thread.start()

    def _run(self, controller_addr: str, extra_env: dict) -> None:
        self.status = RUNNING
        env = {**os.environ, **extra_env,
               "RAY_TPU_ADDRESS": controller_addr}
        try:
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            out, _ = self._proc.communicate()
            self.log = out or ""
            self.return_code = self._proc.returncode
            if self.status != STOPPED:
                self.status = SUCCEEDED if self._proc.returncode == 0 \
                    else FAILED
        except Exception as e:  # noqa: BLE001
            self.log += f"\nsupervisor error: {e}"
            self.status = FAILED

    def get_status(self) -> dict:
        return {"status": self.status, "return_code": self.return_code}

    def get_logs(self) -> str:
        return self.log

    def stop(self) -> bool:
        if self._proc is not None and self._proc.poll() is None:
            self.status = STOPPED
            self._proc.terminate()
            return True
        return False


class _JobManager:
    """Detached registry actor (ray: job_manager.py:57 JobManager)."""

    def __init__(self):
        self.jobs: dict[str, JobInfo] = {}
        self.supervisors: dict[str, object] = {}

    def submit(self, entrypoint: str, job_id: str | None,
               metadata: dict | None, env: dict | None,
               controller_addr: str) -> str:
        job_id = job_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        if job_id in self.jobs:
            raise ValueError(f"job {job_id!r} already exists")
        info = JobInfo(job_id=job_id, entrypoint=entrypoint,
                       start_time=time.time(), status=RUNNING,
                       metadata=metadata or {})
        # num_cpus=0: the supervisor mostly sleeps in communicate(); it
        # must not hold scheduling capacity after the job finishes (the
        # entrypoint subprocess carries the real work).
        sup = ray_tpu.remote(_JobSupervisor).options(
            num_cpus=0, max_concurrency=4).remote(
            job_id, entrypoint, controller_addr, env)
        self.jobs[job_id] = info
        self.supervisors[job_id] = sup
        return job_id

    def status(self, job_id: str) -> dict:
        info = self._info(job_id)
        sup = self.supervisors.get(job_id)
        if sup is not None and info.status in (PENDING, RUNNING):
            st = ray_tpu.get(sup.get_status.remote(), timeout=30.0)
            info.status = st["status"]
            info.return_code = st["return_code"]
            if info.status in (SUCCEEDED, FAILED, STOPPED) \
                    and not info.end_time:
                info.end_time = time.time()
        return vars(info)

    def logs(self, job_id: str) -> str:
        sup = self.supervisors.get(job_id)
        if sup is None:
            return ""
        return ray_tpu.get(sup.get_logs.remote(), timeout=30.0)

    def stop(self, job_id: str) -> bool:
        sup = self.supervisors.get(job_id)
        if sup is None:
            return False
        stopped = ray_tpu.get(sup.stop.remote(), timeout=30.0)
        if stopped:
            self.jobs[job_id].status = STOPPED
        return stopped

    def list(self) -> list[dict]:
        return [self.status(j) for j in list(self.jobs)]

    def _info(self, job_id: str) -> JobInfo:
        if job_id not in self.jobs:
            raise ValueError(f"no job {job_id!r}")
        return self.jobs[job_id]


class _HttpTransport:
    """REST transport against a dashboard (ray: sdk.py's aiohttp calls).
    Selected when the client address is http(s)://."""

    def __init__(self, base_url: str):
        self.base = base_url.rstrip("/")

    def _req(self, method: str, path: str, body: dict | None = None):
        import json as _json
        import urllib.request

        data = _json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return _json.loads(resp.read().decode())

    def submit(self, entrypoint, job_id, metadata, runtime_env):
        return self._req("POST", "/api/jobs/", {
            "entrypoint": entrypoint, "job_id": job_id,
            "metadata": metadata, "runtime_env": runtime_env})["job_id"]

    def info(self, job_id):
        return self._req("GET", f"/api/jobs/{job_id}")

    def logs(self, job_id):
        return self._req("GET", f"/api/jobs/{job_id}/logs")["logs"]

    def stop(self, job_id):
        return self._req("POST", f"/api/jobs/{job_id}/stop")["stopped"]

    def list(self):
        return self._req("GET", "/api/jobs/")


class JobSubmissionClient:
    """ray: dashboard/modules/job/sdk.py JobSubmissionClient — same verbs.
    address=None / "auto": direct actor transport on the connected
    cluster; address="http://host:8265": REST against the dashboard
    (the reference's only transport)."""

    def __init__(self, address: str | None = None):
        self._http: _HttpTransport | None = None
        if address and address.startswith(("http://", "https://")):
            self._http = _HttpTransport(address)
            return
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._mgr = ray_tpu.remote(_JobManager).options(
            name=JOB_MANAGER_NAME, get_if_exists=True, lifetime="detached",
            max_concurrency=16, num_cpus=0).remote()

    def submit_job(self, *, entrypoint: str, job_id: str | None = None,
                   metadata: dict | None = None,
                   runtime_env: dict | None = None) -> str:
        if self._http:
            return self._http.submit(entrypoint, job_id, metadata,
                                     runtime_env)
        from ray_tpu._private.worker import global_worker

        env = dict((runtime_env or {}).get("env_vars") or {})
        return ray_tpu.get(self._mgr.submit.remote(
            entrypoint, job_id, metadata, env,
            global_worker().controller_addr), timeout=60.0)

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id)["status"]

    def get_job_info(self, job_id: str) -> dict:
        if self._http:
            return self._http.info(job_id)
        return ray_tpu.get(self._mgr.status.remote(job_id), timeout=30.0)

    def get_job_logs(self, job_id: str) -> str:
        if self._http:
            return self._http.logs(job_id)
        return ray_tpu.get(self._mgr.logs.remote(job_id), timeout=30.0)

    def stop_job(self, job_id: str) -> bool:
        if self._http:
            return self._http.stop(job_id)
        return ray_tpu.get(self._mgr.stop.remote(job_id), timeout=30.0)

    def list_jobs(self) -> list[dict]:
        if self._http:
            return self._http.list()
        return ray_tpu.get(self._mgr.list.remote(), timeout=60.0)

    def wait_until_finished(self, job_id: str,
                            timeout_s: float = 300.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            st = self.get_job_status(job_id)
            if st in (SUCCEEDED, FAILED, STOPPED):
                return st
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still {st} after {timeout_s}s")
