"""Collective groups over the object plane (the gloo-analog backend).

Analog of ray: python/ray/util/collective/collective.py — same public
functions, same group-name semantics.  Backend: a named `_Rendezvous`
actor per group matches per-(seq, op) contributions from all ranks and
hands back the object refs; each rank then reduces locally.  This is the
DCN control-plane path — for device collectives inside a slice use
jax.lax collectives under pjit/shard_map (ray_tpu.parallel), which XLA
schedules over ICI (SURVEY §2.4).

All-reduce here is gather+local-reduce: O(world) per rank, fine for the
small host counts and small tensors this plane carries (gradients stay on
the ICI plane; this carries host-side state like data-loader offsets,
eval metrics, rendezvous info).
"""
from __future__ import annotations

import threading
from typing import Any

import numpy as np

import ray_tpu

# Process-global group registry (ray: collective.py GroupManager:40 is a
# process singleton).  NOT thread-local: actor methods may run on any
# thread of the actor's pool (max_concurrency > 1).
_registry_lock = threading.Lock()
_registry: dict[str, "_GroupState"] = {}


class _Rendezvous:
    """Named actor: matches contributions from world_size ranks.

    Async actor so waiting ranks don't block each other (the reference's
    rendezvous is the NCCL unique-id store, collective_group/
    nccl_collective_group.py _rendezvous helpers).
    """

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        # (seq, op) -> {"refs": {rank: obj}, "event": asyncio.Event}
        self.pending: dict = {}
        self.asyncio = asyncio

    async def configure(self, world_size: int) -> None:
        """Re-arm for a (re-)created group: a mismatched world_size means a
        new incarnation reused this detached actor's name — old pending
        slots would release collectives early or hand back stale refs."""
        if world_size != self.world_size:
            self.world_size = world_size
            self.pending.clear()
            if hasattr(self, "p2p"):
                self.p2p.clear()

    def _slot(self, key):
        slot = self.pending.get(key)
        if slot is None:
            slot = {"refs": {}, "event": self.asyncio.Event(), "taken": 0}
            self.pending[key] = slot
        return slot

    async def exchange(self, key, rank: int, ref) -> dict:
        """Deposit rank's contribution; wait for all; return all refs."""
        slot = self._slot(tuple(key))
        slot["refs"][rank] = ref
        if len(slot["refs"]) == self.world_size:
            slot["event"].set()
        await slot["event"].wait()
        refs = dict(slot["refs"])
        slot["taken"] += 1
        if slot["taken"] == self.world_size:
            self.pending.pop(tuple(key), None)
        return refs

    def _p2p_queue(self, key):
        if not hasattr(self, "p2p"):
            self.p2p = {}
        q = self.p2p.get(tuple(key))
        if q is None:
            # asyncio.Queue gives FIFO matching of repeated sends with the
            # same (src, dst, tag) — no lost messages on rapid re-send.
            q = self.asyncio.Queue()
            self.p2p[tuple(key)] = q
        return q

    async def put_p2p(self, key, ref) -> None:
        await self._p2p_queue(key).put(ref)

    async def take_p2p(self, key):
        return await self._p2p_queue(key).get()


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, rendezvous):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.rendezvous = rendezvous
        self.seq = 0


def _groups() -> dict:
    return _registry


def init_collective_group(world_size: int, rank: int,
                          backend: str = "object_store",
                          group_name: str = "default") -> None:
    """Join a collective group; call from every participating actor/task
    (ray: collective.py:120)."""
    if rank < 0 or rank >= world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    rdv = ray_tpu.remote(_Rendezvous).options(
        name=f"collective_rdv:{group_name}", get_if_exists=True,
        lifetime="detached", max_concurrency=max(32, world_size * 4),
        num_cpus=0).remote(world_size)
    # A stale rendezvous (same name, earlier group incarnation) must not
    # carry its old world_size or pending slots into this group.
    ray_tpu.get(rdv.configure.remote(world_size))
    with _registry_lock:
        _registry[group_name] = _GroupState(group_name, world_size, rank, rdv)


def create_collective_group(actors: list, world_size: int, ranks: list[int],
                            backend: str = "object_store",
                            group_name: str = "default") -> None:
    """Driver-side declaration (ray: collective.py create_collective_group):
    each actor must expose an `init_collective_group(world_size, rank,
    backend, group_name)` method (typically calling this module's
    init_collective_group)."""
    refs = [a.init_collective_group.remote(world_size, r, backend, group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down the group cluster-wide (ray: collective.py
    destroy_collective_group).  Call only after all ranks are done."""
    with _registry_lock:
        g = _registry.pop(group_name, None)
    if g is not None:
        try:
            ray_tpu.kill(g.rendezvous)
        except Exception:  # noqa: BLE001 - another rank already killed it
            pass


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.world_size if g else -1


def _group(group_name: str) -> _GroupState:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return g


def _exchange(g: _GroupState, op: str, value) -> dict:
    g.seq += 1
    ref = ray_tpu.put(value)
    # Refs ride inside a list: a bare ObjectRef argument is resolved to its
    # value before dispatch (task dependency resolution), but the
    # rendezvous must pass the *ref* through untouched (same wrapping trick
    # as ray: util/collective passing refs in containers).
    refs = ray_tpu.get(g.rendezvous.exchange.remote(
        (op, g.seq), g.rank, [ref]))
    return {r: ray_tpu.get(refs[r][0]) for r in sorted(refs)}


_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "prod": lambda xs: np.prod(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
}


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """ray: collective.py:258.  Returns the reduced array (numpy in,
    numpy out; jax arrays are accepted and returned as numpy)."""
    g = _group(group_name)
    parts = _exchange(g, f"allreduce:{op}", np.asarray(tensor))
    return _REDUCE_OPS[op](np.stack(list(parts.values())))


def allgather(tensor, group_name: str = "default") -> list:
    g = _group(group_name)
    parts = _exchange(g, "allgather", np.asarray(tensor))
    return [parts[r] for r in sorted(parts)]


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    """Each rank gets its 1/world slice of the reduction (ray:
    collective.reducescatter)."""
    g = _group(group_name)
    parts = _exchange(g, f"reducescatter:{op}", np.asarray(tensor))
    reduced = _REDUCE_OPS[op](np.stack(list(parts.values())))
    chunks = np.array_split(reduced, g.world_size, axis=0)
    return chunks[g.rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _group(group_name)
    parts = _exchange(g, f"broadcast:{src_rank}",
                      np.asarray(tensor) if g.rank == src_rank
                      else np.zeros(0))
    return parts[src_rank]


def barrier(group_name: str = "default") -> None:
    g = _group(group_name)
    _exchange(g, "barrier", np.zeros(0))


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """P2P send (ray: collective.send)."""
    g = _group(group_name)
    ref = ray_tpu.put(np.asarray(tensor))
    ray_tpu.get(g.rendezvous.put_p2p.remote(
        (g.rank, dst_rank, tag), [ref]))


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    """P2P recv (ray: collective.recv)."""
    g = _group(group_name)
    wrapped = ray_tpu.get(g.rendezvous.take_p2p.remote(
        (src_rank, g.rank, tag)))
    return ray_tpu.get(wrapped[0])
