"""Collective groups over the object plane (the gloo-analog backend).

Analog of ray: python/ray/util/collective/collective.py — same public
functions, same group-name semantics.  This is the DCN control-plane
path — for device collectives inside a slice use jax.lax collectives
under pjit/shard_map (ray_tpu.parallel), which XLA schedules over ICI
(SURVEY §2.4).

Backends (ISSUE 5):

- **ring / tree** (default): bandwidth-optimal pipelined schedules in
  `ring.py`.  Large tensors (>= RAY_TPU_COLLECTIVE_RING_MIN_BYTES) take
  the ring reduce-scatter + allgather — 2*N*(world-1)/world bytes per
  rank, chunks hopping peer-to-peer as object-plane puts, reduce
  overlapped against transport; small tensors take a binomial tree
  (2*ceil(log2 world) hops, payload inline).  The named `_Rendezvous`
  actor carries only neighbor mailbox matching and seq bookkeeping —
  never bulk payload.
- **legacy gather** (RAY_TPU_RING_COLLECTIVES=0): the original
  "gather all world_size refs, reduce locally" path — O(world*N) bytes
  pulled per rank — kept selectable for same-run A/B.

Async variants (`allreduce_async`, ...) return a wait()-able
`CollectiveWork`; per group, ops execute on a dedicated thread in
submission (seq) order, so a train step can kick off its host-side
sync and overlap the next step's input pipeline.

Every exchange is deadline-bounded: a rank that crashes mid-collective
surfaces on the survivors as a diagnostic error naming the missing
rank(s), never a hang.

Opt-in phase tracer: `ray_tpu.profiling.collective_trace()` /
`collective_breakdown_us()` — per-collective send/pull/reduce/wait
accumulation plus sent/recv byte counters (the schedule-shape proof).
"""
from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any

import numpy as np

import ray_tpu
from ray_tpu import failpoints, memledger, profiling, tracing
from ray_tpu.collective import ring as _ring
from ray_tpu.collective.ring import _env_float, _env_int

# Process-global group registry (ray: collective.py GroupManager:40 is a
# process singleton).  NOT thread-local: actor methods may run on any
# thread of the actor's pool (max_concurrency > 1).
_registry_lock = threading.Lock()
_registry: dict[str, "_GroupState"] = {}

_TRUTHY = ("1", "true", "yes", "on")


def _ring_enabled() -> bool:
    """Kill switch: RAY_TPU_RING_COLLECTIVES=0 restores the legacy
    gather path (same-run A/B; read at call time so a live process can
    flip it)."""
    return os.environ.get(
        "RAY_TPU_RING_COLLECTIVES", "1").lower() in _TRUTHY


def _ring_min_bytes() -> int:
    return _env_int("RAY_TPU_COLLECTIVE_RING_MIN_BYTES", 256 * 1024)


class _Rendezvous:
    """Named actor: neighbor mailbox + per-(seq, op) contribution
    matching.  Async actor so waiting ranks don't block each other (the
    reference's rendezvous is the NCCL unique-id store, collective_group/
    nccl_collective_group.py _rendezvous helpers).  On the ring/tree
    paths it never touches bulk payload — only refs and small inline
    arrays ride through it."""

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        # (seq, op) -> {"refs": {rank: obj}, "event": asyncio.Event,
        #               "taken": int, "error": str | None}
        self.pending: dict = {}
        self.p2p: dict = {}
        self.asyncio = asyncio

    async def configure(self, world_size: int) -> None:
        """Re-arm for a (re-)created group: a mismatched world_size means a
        new incarnation reused this detached actor's name — old pending
        slots would release collectives early or hand back stale refs."""
        if world_size != self.world_size:
            self.world_size = world_size
            self.pending.clear()
            self.p2p.clear()

    def _slot(self, key):
        slot = self.pending.get(key)
        if slot is None:
            slot = {"refs": {}, "event": self.asyncio.Event(), "taken": 0,
                    "error": None}
            self.pending[key] = slot
        return slot

    async def exchange(self, key, rank: int, ref,
                       timeout_s: float | None = None) -> dict:
        """Deposit rank's contribution; wait for all; return all refs.
        Deadline-bounded: on timeout each waiter raises a diagnostic
        naming the ranks that never arrived (satellite: a crashed rank
        must not block its peers forever)."""
        key = tuple(key)
        slot = self._slot(key)
        slot["refs"][rank] = ref
        if len(slot["refs"]) == self.world_size:
            slot["event"].set()
        try:
            if timeout_s is None:
                await slot["event"].wait()
            else:
                await self.asyncio.wait_for(slot["event"].wait(),
                                            timeout_s)
        except self.asyncio.TimeoutError:
            present = sorted(slot["refs"])
            missing = sorted(set(range(self.world_size))
                             - set(slot["refs"]))
            # Late arrivals must not complete against a half-abandoned
            # slot; drop it so they fail fast on their own timeout.
            self.pending.pop(key, None)
            raise TimeoutError(
                f"collective exchange {key} timed out after {timeout_s}s:"
                f" missing ranks {missing} (present: {present}, "
                f"world_size {self.world_size})") from None
        if slot["error"]:
            raise RuntimeError(slot["error"])
        refs = dict(slot["refs"])
        slot["taken"] += 1
        if slot["taken"] == self.world_size:
            self.pending.pop(key, None)
        return refs

    def _p2p_queue(self, key):
        q = self.p2p.get(tuple(key))
        if q is None:
            # asyncio.Queue gives FIFO matching of repeated sends with the
            # same (src, dst, tag) — no lost messages on rapid re-send.
            q = self.asyncio.Queue()
            self.p2p[tuple(key)] = q
        return q

    async def put_p2p(self, key, ref) -> None:
        await self._p2p_queue(key).put(ref)

    async def take_p2p(self, key, timeout_s: float | None = None):
        """Take one mailbox message; deadline-bounded with a diagnostic
        naming the key (whose src rank never delivered) on timeout."""
        key = tuple(key)
        q = self._p2p_queue(key)
        try:
            if timeout_s is None:
                msg = await q.get()
            else:
                msg = await self.asyncio.wait_for(q.get(), timeout_s)
        except self.asyncio.TimeoutError:
            if q.empty():
                self.p2p.pop(key, None)
            raise TimeoutError(
                f"collective p2p take {key} timed out after "
                f"{timeout_s}s: the sending rank never deposited "
                f"(crashed mid-collective? ranks disagreeing on the "
                f"schedule — e.g. heterogeneous tensor sizes straddling "
                f"RAY_TPU_COLLECTIVE_RING_MIN_BYTES?)") from None
        if q.empty():
            self.p2p.pop(key, None)
        if isinstance(msg, dict) and msg.get("__drained__"):
            raise RuntimeError(msg["__drained__"])
        return msg

    async def swap(self, put_key, msg, take_key,
                   timeout_s: float | None = None):
        """One ring hop's mailbox work in ONE round trip: deposit the
        outgoing message, then await the incoming one.  Every rank's
        swap deposits before it waits, so the ring always progresses."""
        await self._p2p_queue(put_key).put(msg)
        return await self.take_p2p(take_key, timeout_s)

    async def drain(self, reason: str) -> int:
        """Fail every parked waiter with `reason` and clear all slots —
        destroy_collective_group calls this before killing the actor so
        blocked peers get a diagnostic error instead of ActorDiedError."""
        n = 0
        for slot in self.pending.values():
            slot["error"] = reason
            slot["event"].set()
            n += 1
        for q in self.p2p.values():
            # One marker per parked getter is enough; extras are GC'd
            # with the actor.
            for _ in range(8):
                q.put_nowait({"__drained__": reason})
            n += 1
        self.pending.clear()
        self.p2p.clear()
        return n

    async def stats(self) -> dict:
        return {"pending_slots": len(self.pending),
                "p2p_queues": len(self.p2p),
                "world_size": self.world_size}


class CollectiveWork:
    """Handle returned by the *_async collectives: `wait()`/`result()`
    block for (and return) the collective's result; exceptions from the
    schedule (timeouts naming missing ranks, ConnectionLost, ...)
    re-raise here."""

    def __init__(self, fut, seq: int):
        self._fut = fut
        self.seq = seq

    def wait(self, timeout: float | None = None):
        return self._fut.result(timeout)

    # ray.get-style alias
    def result(self, timeout: float | None = None):
        return self.wait(timeout)

    def done(self) -> bool:
        return self._fut.done()


class _GroupState:
    def __init__(self, name: str, world_size: int, rank: int, rendezvous,
                 timeout_s: float):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.rendezvous = rendezvous
        self.seq = 0
        self.timeout_s = timeout_s
        self.pipeline_chunks = _env_int(
            "RAY_TPU_COLLECTIVE_PIPELINE_CHUNKS", 4)
        self.pipeline_min_bytes = _env_int(
            "RAY_TPU_COLLECTIVE_PIPELINE_MIN_BYTES", 1 * 1024 * 1024)
        self._lock = threading.Lock()
        # Ordered op pool per group: async and sync collectives share
        # it, so with the default single worker execution order == seq
        # (submission) order.  RAY_TPU_COLLECTIVE_INFLIGHT_OPS>1 lets
        # INDEPENDENT async ops overlap (op k+1's reduce-scatter under
        # op k's allgather — mailbox keys are seq-scoped, so concurrent
        # ops never cross-talk); results still arrive on their own
        # CollectiveWork regardless of completion order.
        self.inflight_ops = max(1, _env_int(
            "RAY_TPU_COLLECTIVE_INFLIGHT_OPS", 1))
        self._ops = ThreadPoolExecutor(
            max_workers=self.inflight_ops,
            thread_name_prefix=f"col-{name}-r{rank}")
        # Prefetch pool: a hop's sub-chunk pulls run concurrently (their
        # round trips overlap) while the reduce consumes them in order —
        # transport of sub-chunk k+1 overlaps the reduce of k.
        self.prefetcher = ThreadPoolExecutor(
            max_workers=max(2, self.pipeline_chunks),
            thread_name_prefix=f"col-pf-{name}-r{rank}")

    def submit(self, fn) -> CollectiveWork:
        """Assign the next seq under the lock and queue `fn(seq)` on the
        ordered op thread.  The caller's trace context is captured HERE
        (API-call time, caller thread) and re-installed around the op —
        the op thread otherwise has no idea which request/step asked."""
        ctx = tracing.capture() if tracing.ENABLED else None

        def run(seq: int):
            with tracing.context(ctx):
                return fn(seq)

        with self._lock:
            self.seq += 1
            seq = self.seq
            fut = self._ops.submit(run, seq)
        return CollectiveWork(fut, seq)

    def close(self) -> None:
        self._ops.shutdown(wait=False)
        self.prefetcher.shutdown(wait=False)


def _groups() -> dict:
    return _registry


def init_collective_group(world_size: int, rank: int,
                          backend: str = "object_store",
                          group_name: str = "default",
                          timeout_s: float | None = None) -> None:
    """Join a collective group; call from every participating actor/task
    (ray: collective.py:120).

    Re-using a group NAME for a new incarnation requires
    `destroy_collective_group` in between (it drains and kills the
    rendezvous, so the re-create binds a FRESH actor — the train restart
    loop does this).  Without a destroy, a same-world re-init reuses the
    detached rendezvous via get_if_exists and `configure` can only scrub
    stale slots when world_size CHANGED: an unconditional clear would
    race a concurrent group creation (rank A's first deposits landing
    while rank B's configure still runs would be wiped)."""
    if rank < 0 or rank >= world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    if timeout_s is None:
        timeout_s = _env_float("RAY_TPU_COLLECTIVE_TIMEOUT_S", 120.0)
    rdv = ray_tpu.remote(_Rendezvous).options(
        name=f"collective_rdv:{group_name}", get_if_exists=True,
        lifetime="detached",
        max_concurrency=max(64, world_size * 8),
        num_cpus=0).remote(world_size)
    # A stale rendezvous (same name, earlier group incarnation) must not
    # carry its old world_size or pending slots into this group.
    ray_tpu.get(rdv.configure.remote(world_size))
    with _registry_lock:
        old = _registry.pop(group_name, None)
        _registry[group_name] = _GroupState(group_name, world_size, rank,
                                            rdv, timeout_s)
    if old is not None:
        old.close()


def create_collective_group(actors: list, world_size: int, ranks: list[int],
                            backend: str = "object_store",
                            group_name: str = "default") -> None:
    """Driver-side declaration (ray: collective.py create_collective_group):
    each actor must expose an `init_collective_group(world_size, rank,
    backend, group_name)` method (typically calling this module's
    init_collective_group)."""
    refs = [a.init_collective_group.remote(world_size, r, backend, group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs)


def deregister_collective_group(group_name: str = "default") -> None:
    """Local-only teardown: drop THIS process's group state (op threads,
    prefetch pool) without touching the shared rendezvous.  The elastic
    train path uses it at a membership-epoch change: the DRIVER destroys
    the stale epoch's group cluster-wide (draining parked waiters);
    each surviving worker only needs to forget its local handle before
    joining the next epoch's group."""
    with _registry_lock:
        g = _registry.pop(group_name, None)
    if g is not None:
        g.close()


def destroy_collective_group(group_name: str = "default",
                             reason: str | None = None) -> None:
    """Tear down the group cluster-wide (ray: collective.py
    destroy_collective_group).  Call only after all ranks are done —
    or, at an elastic epoch change, to UNPARK ranks still waiting on a
    collective with a dead peer: `reason` becomes the diagnostic every
    parked waiter raises (default names the destroy itself).

    Works from ANY process: the pre-round-10 version only killed the
    rendezvous when the calling process had the group in its local
    registry — a driver that formed the group via create_collective_group
    (whose registry is in the ACTORS, not here) leaked the detached
    actor and all its pending slots forever.  Now the named actor is
    resolved directly, drained (parked waiters get a diagnostic error,
    slots are cleared), then killed."""
    with _registry_lock:
        g = _registry.pop(group_name, None)
    rdv = g.rendezvous if g is not None else None
    if g is not None:
        g.close()
    if rdv is None:
        try:
            rdv = ray_tpu.get_actor(f"collective_rdv:{group_name}")
        except Exception:  # noqa: BLE001 - never created / already gone
            rdv = None
    if rdv is not None:
        try:
            ray_tpu.get(rdv.drain.remote(
                reason or f"collective group {group_name!r} destroyed"),
                timeout=10.0)
        except Exception:  # noqa: BLE001 - best effort before the kill
            pass
        try:
            ray_tpu.kill(rdv)
        except Exception:  # noqa: BLE001 - another rank already killed it
            pass
        # Wait (bounded) for the name to release: an immediate re-create
        # of the same group would otherwise get_if_exists the DYING
        # actor and fail its first ops (the controller hides the actor
        # only once it is marked DEAD).
        import time as _t

        deadline = _t.monotonic() + 10.0
        while _t.monotonic() < deadline:
            try:
                ray_tpu.get_actor(f"collective_rdv:{group_name}")
            except Exception:  # noqa: BLE001 - gone
                break
            _t.sleep(0.1)


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.world_size if g else -1


def _group(group_name: str) -> _GroupState:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this "
            f"process; call init_collective_group first")
    return g


# ------------------------------------------------------------ legacy path
def _exchange(g: _GroupState, op: str, value, seq: int) -> dict:
    if failpoints.ACTIVE:
        failpoints.fire("collective.chunk_send")
    with memledger.tag("collective_chunk",
                       label="collective/collective.py exchange"):
        ref = ray_tpu.put(value)
    # Refs ride inside a list: a bare ObjectRef argument is resolved to its
    # value before dispatch (task dependency resolution), but the
    # rendezvous must pass the *ref* through untouched (same wrapping trick
    # as ray: util/collective passing refs in containers).
    refs = ray_tpu.get(g.rendezvous.exchange.remote(
        (op, seq), g.rank, [ref], g.timeout_s),
        timeout=g.timeout_s + 30.0)
    return {r: ray_tpu.get(refs[r][0]) for r in sorted(refs)}


_REDUCE_OPS = {
    "sum": lambda xs: np.sum(xs, axis=0),
    "prod": lambda xs: np.prod(xs, axis=0),
    "max": lambda xs: np.max(xs, axis=0),
    "min": lambda xs: np.min(xs, axis=0),
}


def _gather_parts(g: _GroupState, tag: str, value, seq: int,
                  rec: dict | None) -> dict:
    """Legacy transport: every rank's ref through the rendezvous, every
    rank pulls all of them — O(world*N) bytes per rank, which is exactly
    what the tracer shows vs the ring."""
    parts = _exchange(g, tag, value, seq)
    if rec is not None:
        rec["sent_bytes"] += getattr(value, "nbytes", 0)
        rec["recv_bytes"] += sum(
            getattr(v, "nbytes", 0) for r, v in parts.items()
            if r != g.rank)
        rec["hops"] += 1
    return parts


def _legacy_reduce(parts: dict, op: str, rec: dict | None) -> np.ndarray:
    if failpoints.ACTIVE:
        failpoints.fire("collective.reduce")
    import time as _t

    t0 = _t.monotonic()
    out = _REDUCE_OPS[op](np.stack(list(parts.values())))
    if rec is not None:
        rec["reduce_us"] += (_t.monotonic() - t0) * 1e6
    return out


# --------------------------------------------------------- schedule pick
def _pick_schedule(nbytes: int) -> str:
    if not _ring_enabled():
        return "gather"
    return "ring" if nbytes >= _ring_min_bytes() else "tree"


def _traced(g: _GroupState, schedule: str, op: str, tensor,
            seq: int, fn):
    """Run one collective body with phase accounting around it: the
    opt-in one-shot tracer when armed, and — always, unless
    RAY_TPU_TRACE=0 — a flight-recorder span per op carrying the same
    send/pull/reduce/wait phase sums the schedules already stamp into
    the record (the per-collective attribution of "which phase ate
    this train step")."""
    rec = profiling.consume_collective_arm()
    armed = rec is not None
    if not armed and tracing.ENABLED:
        rec = profiling.blank_collective_rec()
    if rec is not None:
        rec.update(schedule=schedule, op=op,
                   bytes=int(getattr(tensor, "nbytes", 0)),
                   world=g.world_size, rank=g.rank, seq=seq)
    t_span0 = time.time()
    err = None
    try:
        return fn(rec)
    except BaseException as e:  # noqa: BLE001 - recorded, re-raised
        err = type(e).__name__
        raise
    finally:
        if armed:
            # publish also bridges the record into the recorder.
            profiling.publish_collective_trace(rec)
        elif rec is not None:
            attrs = {k: rec[k] for k in
                     ("schedule", "op", "bytes", "world", "rank", "seq",
                      "hops", "sent_bytes", "recv_bytes") if k in rec}
            for k in profiling.COLLECTIVE_PHASES:
                if rec.get(k):
                    attrs[k] = round(rec[k], 1)
            if err:
                attrs["error"] = err
            tracing.emit(f"collective.{op}", t_span0, attrs=attrs)


# ------------------------------------------------------------- public API
def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """ray: collective.py:258.  Returns the reduced array (numpy in,
    numpy out; jax arrays are accepted and returned as numpy)."""
    return allreduce_async(tensor, group_name, op).wait()


def allreduce_async(tensor, group_name: str = "default",
                    op: str = "sum") -> CollectiveWork:
    """Async allreduce: returns a wait()-able CollectiveWork so the
    caller overlaps the DCN sync with other work (train: next step's
    input pipeline).  Per group, ops run in submission order."""
    g = _group(group_name)
    x = np.asarray(tensor)
    schedule = _pick_schedule(x.nbytes)

    def run(seq: int):
        def body(rec):
            if schedule == "ring":
                return _ring.ring_allreduce(g, x, op, seq, rec)
            if schedule == "tree":
                return _ring.tree_allreduce(g, x, op, seq, rec)
            return _legacy_reduce(
                _gather_parts(g, f"allreduce:{op}", x, seq, rec), op,
                rec)
        return _traced(g, schedule, f"allreduce:{op}", x, seq, body)

    return g.submit(run)


def allgather(tensor, group_name: str = "default") -> list:
    return allgather_async(tensor, group_name).wait()


def allgather_async(tensor,
                    group_name: str = "default") -> CollectiveWork:
    """NOTE: the ring path (>= RAY_TPU_COLLECTIVE_RING_MIN_BYTES)
    requires same-shape tensors on every rank (MPI_Allgather contract);
    heterogeneous shapes need the legacy path
    (RAY_TPU_RING_COLLECTIVES=0)."""
    g = _group(group_name)
    x = np.asarray(tensor)
    schedule = _pick_schedule(x.nbytes)
    if schedule == "tree":
        schedule = "gather"      # below the ring threshold the legacy
        # exchange IS the latency-optimal allgather (1 matched exchange)

    def run(seq: int):
        def body(rec):
            if schedule == "ring":
                return _ring.ring_allgather(g, x, seq, rec)
            parts = _gather_parts(g, "allgather", x, seq, rec)
            return [parts[r] for r in sorted(parts)]
        return _traced(g, schedule, "allgather", x, seq, body)

    return g.submit(run)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    """Each rank gets its 1/world slice of the reduction (ray:
    collective.reducescatter)."""
    return reducescatter_async(tensor, group_name, op).wait()


def reducescatter_async(tensor, group_name: str = "default",
                        op: str = "sum") -> CollectiveWork:
    g = _group(group_name)
    x = np.asarray(tensor)
    schedule = _pick_schedule(x.nbytes)

    def run(seq: int):
        def body(rec):
            if schedule == "ring":
                return _ring.ring_reducescatter(g, x, op, seq, rec)
            if schedule == "tree":
                # Latency regime: tree-allreduce then slice — same hop
                # count as a dedicated halving schedule at these sizes,
                # zero extra code paths to verify.
                reduced = _ring.tree_allreduce(g, x, op, seq, rec)
                return np.array_split(reduced, g.world_size,
                                      axis=0)[g.rank]
            parts = _gather_parts(g, f"reducescatter:{op}", x, seq, rec)
            reduced = _legacy_reduce(parts, op, rec)
            chunks = np.array_split(reduced, g.world_size, axis=0)
            return chunks[g.rank]
        return _traced(g, schedule, f"reducescatter:{op}", x, seq, body)

    return g.submit(run)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    return broadcast_async(tensor, src_rank, group_name).wait()


def broadcast_async(tensor, src_rank: int = 0,
                    group_name: str = "default") -> CollectiveWork:
    g = _group(group_name)
    # Non-src ranks don't know the payload size, so broadcast can't be
    # size-gated consistently: tree whenever ring collectives are on.
    schedule = "tree" if _ring_enabled() else "gather"
    x = np.asarray(tensor) if g.rank == src_rank else None

    def run(seq: int):
        def body(rec):
            if schedule == "tree":
                return _ring.tree_broadcast(g, x, src_rank, seq, rec)
            parts = _gather_parts(
                g, f"broadcast:{src_rank}",
                x if g.rank == src_rank else np.zeros(0), seq, rec)
            return parts[src_rank]
        return _traced(g, schedule, f"broadcast:{src_rank}",
                       x if x is not None else np.zeros(0), seq, body)

    return g.submit(run)


class _MappedWork(CollectiveWork):
    """CollectiveWork whose result is `fn(inner result)` — computed once
    on the first wait (on the WAITER's thread, not the group op thread:
    unpacking must not serialize behind other queued collectives)."""

    _UNSET = object()

    def __init__(self, inner: CollectiveWork, fn):
        self._inner = inner
        self._fn = fn
        self.seq = inner.seq
        self._out = _MappedWork._UNSET

    def wait(self, timeout: float | None = None):
        if self._out is _MappedWork._UNSET:
            self._out = self._fn(self._inner.wait(timeout))
        return self._out

    def done(self) -> bool:
        return self._inner.done()


def broadcast_pytree(tree, src_rank: int = 0,
                     group_name: str = "default"):
    return broadcast_pytree_async(tree, src_rank, group_name).wait()


def broadcast_pytree_async(tree, src_rank: int = 0,
                           group_name: str = "default") -> CollectiveWork:
    """Broadcast a whole pytree of arrays as ONE transport (the online
    RLHF weight-sync path: a llama param tree is hundreds of leaves —
    per-leaf broadcasts would pay the tree/ring hop latency per leaf;
    packing them into a single contiguous byte buffer pays it once and
    lets the ring/tree schedule see one large tensor).

    Contract: every rank passes a tree of the SAME structure and leaf
    shapes/dtypes — non-src ranks' trees serve as the unpack template
    (natural for weight sync, where each receiver already holds the
    previous weights).  Returns the src tree's values unflattened into
    the caller's structure; leaves come back as numpy arrays on non-src
    ranks (src gets its own tree back untouched).  A byte-size mismatch
    (structures drifted) raises a diagnostic instead of mis-slicing."""
    import jax

    g = _group(group_name)      # fail fast on the caller's thread
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if g.rank == src_rank:
        # Device leaves: kick every transfer before materializing any
        # (a synchronous per-leaf fetch through a tunneled chip pays
        # the full RTT per leaf — the very cost packing exists to
        # avoid; same pattern as the serve KV-export path).
        for x in leaves:
            try:
                x.copy_to_host_async()
            except AttributeError:
                pass
    arrs = [np.ascontiguousarray(x) for x in leaves]
    total = sum(a.nbytes for a in arrs)
    if g.rank == src_rank:
        payload = np.empty(total, np.uint8)
        off = 0
        for a in arrs:
            n = a.nbytes
            if n:
                payload[off:off + n] = a.reshape(-1).view(np.uint8)
            off += n
    else:
        payload = None
    work = broadcast_async(payload, src_rank, group_name)

    def unpack(flat):
        if g.rank == src_rank:
            return tree
        flat = np.asarray(flat).reshape(-1).view(np.uint8)
        if flat.nbytes != total:
            raise RuntimeError(
                f"broadcast_pytree: received {flat.nbytes} bytes but "
                f"this rank's template tree holds {total} — src and "
                "receiver param trees have drifted (different model "
                "config / stale template?)")
        out, off = [], 0
        for a in arrs:
            n = a.nbytes
            out.append(flat[off:off + n].view(a.dtype).reshape(a.shape))
            off += n
        return jax.tree_util.tree_unflatten(treedef, out)

    return _MappedWork(work, unpack)


def barrier(group_name: str = "default") -> None:
    g = _group(group_name)

    def run(seq: int):
        _exchange(g, "barrier", np.zeros(0), seq)

    g.submit(run).wait()


def send(tensor, dst_rank: int, group_name: str = "default",
         tag: int = 0) -> None:
    """P2P send (ray: collective.send)."""
    g = _group(group_name)
    with memledger.tag("collective_chunk",
                       label="collective/collective.py send"):
        ref = ray_tpu.put(np.asarray(tensor))
    ray_tpu.get(g.rendezvous.put_p2p.remote(
        (g.rank, dst_rank, tag), [ref]), timeout=g.timeout_s + 30.0)


def recv(src_rank: int, group_name: str = "default", tag: int = 0):
    """P2P recv (ray: collective.recv)."""
    g = _group(group_name)
    wrapped = ray_tpu.get(g.rendezvous.take_p2p.remote(
        (src_rank, g.rank, tag), g.timeout_s),
        timeout=g.timeout_s + 30.0)
    return ray_tpu.get(wrapped[0])
