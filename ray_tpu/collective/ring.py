"""Bandwidth-optimal pipelined DCN collective schedules (ISSUE 5).

Schedules over the object plane replacing the legacy "gather all
world_size refs, reduce locally" backend (O(world*N) bytes pulled per
rank):

- **Ring** (large tensors): reduce-scatter + allgather (Thakur et al.
  2005; Horovod).  Each rank moves 2*N*(world-1)/world bytes regardless
  of world size; every chunk hops peer-to-peer as object-plane puts
  (the PR 2 streaming write kernel and chunked pulls carry the bytes),
  with the hop's payload split into sub-chunks whose pulls run
  concurrently on a prefetch pool while the local reduce consumes them
  in order — transport of sub-chunk k+1 overlaps the reduce of k.  The
  rendezvous mailbox carries ONE message per hop (the sub-chunk ref
  list), so per-hop control cost is 2 round trips, not O(sub-chunks)
  (count RTs, not ms, per CLAUDE.md).
- **Binomial tree** (small tensors): 2*ceil(log2 world) hops with the
  payload inline in the mailbox message — round trips dominate under
  the size threshold, so no put/pull indirection at all.

Reduction-order note: the ring accumulates chunk c along the ring
(rank c+1, c+2, ... c), the tree along the binomial recursion, and the
legacy path over a stacked axis — all three are exact for min/max, any
integer dtype, and float values without rounding (integers within the
mantissa); float sums that round may differ in final ULPs between
schedules, as with any collective library.

This is library-layer code: only public surfaces (`ray_tpu` core API,
`ray_tpu.profiling`, `ray_tpu.failpoints`) — never runtime internals
(enforced by tests/test_layering.py).
"""
from __future__ import annotations

import threading
import time

import numpy as np

import ray_tpu
from ray_tpu import failpoints, memledger

# Binary reduce ops (the legacy gather path reduces a stacked axis; the
# ring/tree paths fold pairwise).
BINARY_OPS = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
}


# Env knob readers, shared with collective.py (which imports this
# module; defining them there instead would make an import cycle).
def _env_int(name: str, default: int) -> int:
    import os

    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _bcast_by_ref(nbytes: int) -> bool:
    """Broadcast payload transport: inline through the mailbox below the
    ring threshold, object-plane refs above it — bulk bytes must never
    ride the rendezvous actor."""
    return nbytes >= _env_int("RAY_TPU_COLLECTIVE_RING_MIN_BYTES",
                              256 * 1024)


def _now() -> float:
    return time.monotonic()


# Tracer records are mutated from the op thread AND the prefetch-pool
# threads (concurrent sub-chunk pulls); dict `+=` is not atomic across
# bytecode boundaries, and recv_bytes is the schedule proof the bench
# and tests assert on — guard every accumulation.
_REC_LOCK = threading.Lock()


def _acc(rec: dict | None, key: str, t0: float) -> None:
    if rec is not None:
        with _REC_LOCK:
            rec[key] += (_now() - t0) * 1e6


def _count(rec: dict | None, key: str, nbytes: int) -> None:
    if rec is not None:
        with _REC_LOCK:
            rec[key] += int(nbytes)


def _split_subchunks(chunk: np.ndarray, pipeline_chunks: int,
                     pipeline_min_bytes: int) -> list[np.ndarray]:
    """Sub-chunks of one ring hop's payload: enough pieces that pulls
    pipeline, each big enough that per-object overhead stays amortized."""
    if chunk.nbytes <= 0:
        return [chunk]
    p = max(1, min(pipeline_chunks,
                   chunk.nbytes // max(1, pipeline_min_bytes)))
    return np.array_split(chunk, p)


def _deposit(g, key: tuple, payload_chunks: list[np.ndarray], *,
             by_ref: bool, rec: dict | None, holds: list,
             pending: list) -> None:
    """Hand one hop's payload to the peer via the rendezvous mailbox —
    by ref (one object-plane put per sub-chunk; the peer pulls the bytes
    peer-to-peer) or inline (small-tensor path).  One mailbox message
    per hop either way."""
    if failpoints.ACTIVE:
        failpoints.fire("collective.chunk_send")
    t0 = _now()
    if by_ref:
        with memledger.tag("collective_chunk",
                           label="collective/ring.py hop deposit"):
            msg = [ray_tpu.put(c) for c in payload_chunks]
        # The sender's handles keep the chunks alive until the op's
        # completion ack proves the peer pulled them.
        holds.extend(msg)
    else:
        msg = list(payload_chunks)
    # Fire-and-forget: deposits pipeline behind each other; delivery is
    # confirmed in one batch by _settle() at op end.
    pending.append(g.rendezvous.put_p2p.remote(key, msg))
    _acc(rec, "send_us", t0)
    _count(rec, "sent_bytes",
           sum(getattr(c, "nbytes", 0) for c in payload_chunks))


def _submit_take(g, key: tuple):
    """Start a mailbox take; the actor side bounds the wait and names
    the missing peer on timeout (not a hang)."""
    return g.rendezvous.take_p2p.remote(key, g.timeout_s)


def _pull_one(g, ref, rec: dict | None) -> np.ndarray:
    """Pull one sub-chunk through the object plane (prefetch-pool
    thread: pulls run concurrently and overlap the in-order reduce)."""
    t0 = _now()
    val = ray_tpu.get(ref, timeout=g.timeout_s)
    _acc(rec, "pull_us", t0)
    _count(rec, "recv_bytes", getattr(val, "nbytes", 0))
    return val


def _as_parts(g, msg: list, rec: dict | None) -> list:
    """Turn one hop's mailbox message into in-order payload parts: pull
    futures for by-ref sub-chunks (the pulls run concurrently on the
    prefetch pool, overlapping the in-order reduce), values for inline
    payloads."""
    if msg and isinstance(msg[0], ray_tpu.ObjectRef):
        return [g.prefetcher.submit(_pull_one, g, r, rec) for r in msg]
    _count(rec, "recv_bytes",
           sum(getattr(v, "nbytes", 0) for v in msg))
    return msg


def _recv_hop(g, key: tuple, rec: dict | None) -> list:
    """Take one hop's mailbox message (tree path: receive-only ranks)."""
    t0 = _now()
    msg = ray_tpu.get(_submit_take(g, key), timeout=g.timeout_s + 30.0)
    _acc(rec, "wait_us", t0)
    if rec is not None:
        rec["hops"] += 1
    return _as_parts(g, msg, rec)


def _swap_msg(g, put_key: tuple, msg: list, take_key: tuple,
              rec: dict | None) -> list:
    """ONE `swap` round trip: deposit the outgoing hop message, return
    the incoming one — the entire per-hop mailbox cost is a single RT."""
    if failpoints.ACTIVE:
        failpoints.fire("collective.chunk_send")
    t0 = _now()
    incoming = ray_tpu.get(
        g.rendezvous.swap.remote(put_key, msg, take_key, g.timeout_s),
        timeout=g.timeout_s + 30.0)
    _acc(rec, "wait_us", t0)
    if rec is not None:
        rec["hops"] += 1
    return incoming


def _put_chunks(g, payload_chunks: list[np.ndarray], rec: dict | None,
                holds: list) -> list:
    """Put one hop's sub-chunks into the object plane; the handles stay
    in `holds` until the op's completion ack proves the peers pulled."""
    t0 = _now()
    with memledger.tag("collective_chunk",
                       label="collective/ring.py ring hop"):
        msg = [ray_tpu.put(c) for c in payload_chunks]
    holds.extend(msg)
    _acc(rec, "send_us", t0)
    _count(rec, "sent_bytes", sum(c.nbytes for c in payload_chunks))
    return msg


def _swap_hop(g, put_key: tuple, payload_chunks: list[np.ndarray],
              take_key: tuple, rec: dict | None, holds: list) -> list:
    """One ring hop: put the outgoing sub-chunks, swap their refs for
    the incoming hop's message, hand back in-order payload parts."""
    msg = _put_chunks(g, payload_chunks, rec, holds)
    return _as_parts(g, _swap_msg(g, put_key, msg, take_key, rec), rec)


def _consume(part) -> np.ndarray:
    return part.result() if hasattr(part, "result") else part


def _settle(g, pending: list, holds: list, seq: int,
            rec: dict | None, *, ack: bool) -> None:
    """Op epilogue: confirm every mailbox deposit landed, then (ring
    paths) run the neighbor completion ack — the downstream peer
    deposits an ack only after it consumed everything we sent, so our
    chunk refs can be dropped without racing its pulls.  The ack is one
    swap: deposit ours to the upstream peer, await the downstream's."""
    t0 = _now()
    if pending:
        ray_tpu.get(pending, timeout=g.timeout_s + 30.0)
    if ack and g.world_size > 1:
        me, w = g.rank, g.world_size
        up, down = (me - 1) % w, (me + 1) % w
        ray_tpu.get(g.rendezvous.swap.remote(
            (seq, "ack", 0, me, up), [True],
            (seq, "ack", 0, down, me), g.timeout_s),
            timeout=g.timeout_s + 30.0)
    _acc(rec, "wait_us", t0)
    holds.clear()


def _reduce_into(binop, incoming: np.ndarray, own: np.ndarray,
                 rec: dict | None,
                 out: np.ndarray | None = None) -> np.ndarray:
    if failpoints.ACTIVE:
        failpoints.fire("collective.reduce")
    t0 = _now()
    # out= writes straight into the caller's (pre-allocated) buffer —
    # the ring paths hand hop/result slices here so no per-hop
    # intermediate arrays get allocated, copied, then concatenated.
    res = binop(incoming, own) if out is None \
        else binop(incoming, own, out=out)
    _acc(rec, "reduce_us", t0)
    return res


# --------------------------------------------------------------- ring
def _ring_reduce_scatter(g, chunk_views: list[np.ndarray], op: str,
                         seq: int, rec: dict | None, holds: list,
                         out_final: np.ndarray,
                         phase: str = "rs") -> np.ndarray:
    """Ring reduce-scatter over world_size flat chunks.  W-1 hops; at
    step s rank r forwards the partial for chunk (r-s-1) mod W to r+1
    and folds its own contribution into chunk (r-s-2) mod W, so rank r
    ends owning the fully reduced chunk r — written into `out_final`
    (a caller slice) on the last hop.  Intermediate hops ping through
    ONE scratch buffer: the hop's deposit has already copied the
    partial into the arena before the buffer is overwritten.  Bytes per
    rank: N*(world-1)/world."""
    w, r = g.world_size, g.rank
    binop = BINARY_OPS[op]
    nxt, prv = (r + 1) % w, (r - 1) % w
    scratch = np.empty(max(len(c) for c in chunk_views),
                       dtype=out_final.dtype) if w > 2 else None
    acc: np.ndarray | None = None
    for s in range(w - 1):
        send_idx = (r - s - 1) % w
        recv_idx = (r - s - 2) % w
        send_data = chunk_views[send_idx] if s == 0 else acc
        own = chunk_views[recv_idx]
        target = out_final if s == w - 2 else scratch[:len(own)]
        incoming = _swap_hop(
            g, (seq, phase, s, r, nxt),
            _split_subchunks(send_data, g.pipeline_chunks,
                             g.pipeline_min_bytes),
            (seq, phase, s, prv, r), rec, holds)
        own_subs = np.array_split(own, len(incoming))
        tgt_subs = np.array_split(target, len(incoming))
        for part, own_sub, tgt_sub in zip(incoming, own_subs, tgt_subs):
            _reduce_into(binop, _consume(part), own_sub, rec,
                         out=tgt_sub)
        acc = target
    if acc is None:          # world_size == 1
        np.copyto(out_final, chunk_views[0])
        acc = out_final
    return acc


def _ring_allgather_chunks(g, slices: list[np.ndarray], my_idx: int,
                           seq: int, rec: dict | None, holds: list,
                           phase: str = "ag") -> None:
    """Ring allgather into pre-placed output slices: `slices[my_idx]`
    already holds this rank's chunk; W-1 store-and-forward hops fill
    the rest in place (at step s rank r forwards chunk (r-s) mod W and
    receives chunk (r-s-1) mod W).  Hops re-put the forwarded bytes —
    every pull then hits the NEIGHBOR's node and the borrow chain stays
    one hop deep (forwarding the origin's refs instead was measured
    slower: each forwarded borrow adds a cross-owner ack round trip on
    the critical path).  Bytes per rank: sum of the other chunks."""
    w, r = g.world_size, g.rank
    nxt, prv = (r + 1) % w, (r - 1) % w
    for s in range(w - 1):
        send_idx = (r - s) % w
        recv_idx = (r - s - 1) % w
        parts = _swap_hop(
            g, (seq, phase, s, r, nxt),
            _split_subchunks(slices[send_idx], g.pipeline_chunks,
                             g.pipeline_min_bytes),
            (seq, phase, s, prv, r), rec, holds)
        vals = [_consume(p) for p in parts]
        got = sum(v.size for v in vals)
        if got != slices[recv_idx].size:
            raise ValueError(
                f"ring allgather requires same-shape tensors on every "
                f"rank (hop {s}: got {got} elements for chunk "
                f"{recv_idx}, expected {slices[recv_idx].size}); use "
                f"RAY_TPU_RING_COLLECTIVES=0 for heterogeneous shapes")
        tgt_subs = np.array_split(slices[recv_idx], len(vals))
        t0 = _now()
        for val, tgt in zip(vals, tgt_subs):
            # Copy out of the zero-copy read view into the output slice
            # (releases the arena pin as soon as the ref drops).
            np.copyto(tgt, val)
        _acc(rec, "reduce_us", t0)


def ring_allreduce(g, tensor: np.ndarray, op: str, seq: int,
                   rec: dict | None) -> np.ndarray:
    """Ring allreduce = reduce-scatter + allgather over the flattened
    tensor: 2*N*(world-1)/world bytes per rank.  Both phases write
    straight into one pre-allocated result buffer — the allgather
    forwards result slices, so no intermediate copies."""
    x = np.ascontiguousarray(tensor)
    w = g.world_size
    if w == 1:
        return np.array(x, copy=True)
    flat = x.reshape(-1)
    chunk_views = np.array_split(flat, w)
    result = np.empty_like(flat)
    out_slices = np.array_split(result, w)
    holds: list = []
    # Ring hops confirm delivery inside each swap — no deferred
    # deposits to settle (the tree paths are the ones that batch them).
    _ring_reduce_scatter(g, chunk_views, op, seq, rec, holds,
                         out_final=out_slices[g.rank])
    _ring_allgather_chunks(g, out_slices, g.rank, seq, rec, holds)
    _settle(g, [], holds, seq, rec, ack=True)
    return result.reshape(x.shape)


def ring_reducescatter(g, tensor: np.ndarray, op: str, seq: int,
                       rec: dict | None) -> np.ndarray:
    """Ring reduce-scatter with the legacy output contract: rank r gets
    the reduction's r-th `np.array_split(..., axis=0)` slice.  Bytes
    per rank: N*(world-1)/world."""
    x = np.ascontiguousarray(tensor)
    w = g.world_size
    axis_chunks = np.array_split(x, w, axis=0)
    if w == 1:
        return np.array(axis_chunks[0], copy=True)
    chunk_views = [c.reshape(-1) for c in axis_chunks]
    out = np.empty(axis_chunks[g.rank].shape, dtype=x.dtype)
    holds: list = []
    _ring_reduce_scatter(g, chunk_views, op, seq, rec, holds,
                         out_final=out.reshape(-1))
    _settle(g, [], holds, seq, rec, ack=True)
    return out


def ring_allgather(g, tensor: np.ndarray, seq: int,
                   rec: dict | None) -> list[np.ndarray]:
    """Ring allgather of same-shape per-rank tensors (the group
    contract, as in MPI_Allgather): W-1 store-and-forward hops,
    N*(world-1) bytes per rank."""
    x = np.ascontiguousarray(tensor)
    w = g.world_size
    if w == 1:
        return [np.array(x, copy=True)]
    outs = [np.empty_like(x) for _ in range(w)]
    np.copyto(outs[g.rank], x)
    holds: list = []
    _ring_allgather_chunks(g, [o.reshape(-1) for o in outs], g.rank,
                           seq, rec, holds)
    _settle(g, [], holds, seq, rec, ack=True)
    return outs


# --------------------------------------------------------- binomial tree
def tree_allreduce(g, tensor: np.ndarray, op: str, seq: int,
                   rec: dict | None) -> np.ndarray:
    """Binomial-tree allreduce for the latency regime: reduce to rank 0
    (ceil(log2 W) hops), broadcast back down (same).  Payloads ride
    inline in the mailbox message — no put/pull round trips."""
    w, r = g.world_size, g.rank
    acc = np.asarray(tensor)
    if w == 1:
        return np.array(acc, copy=True)
    binop = BINARY_OPS[op]
    pending: list = []
    holds: list = []
    # -- reduce up --
    mask = 1
    while mask < w:
        if r & mask:
            dst = r - mask
            _deposit(g, (seq, "tr", mask, r, dst), [acc], by_ref=False,
                     rec=rec, holds=holds, pending=pending)
            break
        src = r + mask
        if src < w:
            incoming = _consume(_recv_hop(
                g, (seq, "tr", mask, src, r), rec)[0])
            acc = _reduce_into(binop, acc, incoming, rec)
        mask <<= 1
    peel = (r & -r) if r else 0
    # -- broadcast down (mirror) --
    if r != 0:
        parent = r - peel
        acc = np.asarray(_consume(_recv_hop(
            g, (seq, "tb", peel, parent, r), rec)[0]))
    m = (peel >> 1) if r else 1
    if r == 0:
        while m < w:
            m <<= 1
        m >>= 1
    while m >= 1:
        child = r + m
        if child < w:
            _deposit(g, (seq, "tb", m, r, child), [acc], by_ref=False,
                     rec=rec, holds=holds, pending=pending)
        m >>= 1
    _settle(g, pending, holds, seq, rec, ack=False)
    return np.array(acc, copy=True)


def tree_broadcast(g, tensor: np.ndarray | None, src: int, seq: int,
                   rec: dict | None) -> np.ndarray:
    """Binomial-tree broadcast from `src`, ceil(log2 W) hops.  Non-src
    ranks don't know the size, so the TOPOLOGY can't be size-gated —
    but the transport per edge is: each sender ships small payloads
    inline in the mailbox message and large ones as object-plane
    sub-chunk refs (axis-0 split, so concatenation restores the shape);
    receivers just follow what arrives.  Bulk bytes never ride the
    rendezvous actor."""
    w, r = g.world_size, g.rank
    if w == 1:
        return np.array(np.asarray(tensor), copy=True)
    vr = (r - src) % w
    pending: list = []
    holds: list = []
    data = np.asarray(tensor) if vr == 0 else None
    peel = (vr & -vr) if vr else 0
    if vr != 0:
        parent_vr = vr - peel
        parts = _recv_hop(
            g, (seq, "bc", peel, (parent_vr + src) % w, r), rec)
        vals = [np.asarray(_consume(p)) for p in parts]
        data = vals[0] if len(vals) == 1 else np.concatenate(vals)
    m = (peel >> 1) if vr else 1
    if vr == 0:
        while m < w:
            m <<= 1
        m >>= 1
    by_ref = bool(data.ndim) and _bcast_by_ref(data.nbytes)
    payload = _split_subchunks(data, g.pipeline_chunks,
                               g.pipeline_min_bytes) if by_ref \
        else [data]
    children = []
    while m >= 1:
        child_vr = vr + m
        if child_vr < w:
            child = (child_vr + src) % w
            _deposit(g, (seq, "bc", m, r, child), payload,
                     by_ref=by_ref, rec=rec, holds=holds,
                     pending=pending)
            children.append(child)
        m >>= 1
    if by_ref and vr != 0:
        # Consumed ack to the parent: its chunk refs may drop.
        _deposit(g, (seq, "bca", 0, r, (parent_vr + src) % w), [True],
                 by_ref=False, rec=rec, holds=holds, pending=pending)
    _settle(g, pending, holds if not (by_ref and children) else [],
            seq, rec, ack=False)
    if by_ref and children:
        # Our holds drop only after every child consumed what we sent
        # (same insurance as the ring paths' neighbor ack).
        for child in children:
            ray_tpu.get(_submit_take(g, (seq, "bca", 0, child, r)),
                        timeout=g.timeout_s + 30.0)
        holds.clear()
    return np.array(data, copy=True)
