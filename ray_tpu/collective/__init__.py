"""ray_tpu.collective: explicit collective groups across actors/tasks.

Analog of ray: python/ray/util/collective/collective.py (GroupManager:40,
init_collective_group:120, allreduce:258) with NCCL/GLOO backends
(collective_group/nccl_collective_group.py, gloo_collective_group.py).

TPU-first split (SURVEY §2.4 "Collective backend"):
- *Within a slice* collectives are XLA's job: jax.lax.psum/all_gather/
  ppermute inside pjit/shard_map over a Mesh — no runtime API needed, the
  compiler schedules ICI.  This module is NOT that path.
- *Across actor processes* (hosts over DCN) this module provides the
  gloo-analog control-plane collectives: host numpy/jax arrays moved
  through the object store with a named rendezvous actor per group.
  Since round 10 the transport is bandwidth-optimal: ring
  reduce-scatter/allgather for large tensors (chunked, pipelined,
  2*N*(world-1)/world bytes per rank), a binomial tree for small ones,
  async variants (`allreduce_async` → wait()-able CollectiveWork), and
  an opt-in per-collective phase tracer
  (`ray_tpu.profiling.collective_trace`).  Kill switch
  `RAY_TPU_RING_COLLECTIVES=0` restores the legacy gather path.
"""
from ray_tpu.collective.collective import (CollectiveWork, allgather,
                                           allgather_async, allreduce,
                                           allreduce_async, barrier,
                                           broadcast, broadcast_async,
                                           broadcast_pytree,
                                           broadcast_pytree_async,
                                           create_collective_group,
                                           deregister_collective_group,
                                           destroy_collective_group,
                                           get_rank, get_collective_group_size,
                                           init_collective_group, recv,
                                           reducescatter,
                                           reducescatter_async, send)

__all__ = [
    "init_collective_group", "create_collective_group",
    "destroy_collective_group", "deregister_collective_group",
    "allreduce", "allgather", "reducescatter",
    "broadcast", "barrier", "send", "recv", "get_rank",
    "get_collective_group_size", "allreduce_async", "allgather_async",
    "reducescatter_async", "broadcast_async", "broadcast_pytree",
    "broadcast_pytree_async", "CollectiveWork",
]
