"""Experiment-loop callbacks (ray: python/ray/tune/callback.py).

The TuneController invokes each hook; exceptions in user callbacks are
logged, never fatal to the experiment (matching the reference's
error-isolated callback dispatch).
"""
from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


class Callback:
    def on_trial_start(self, iteration: int, trials: list, trial,
                       **info) -> None:
        pass

    def on_trial_result(self, iteration: int, trials: list, trial,
                        result: dict, **info) -> None:
        pass

    def on_trial_complete(self, iteration: int, trials: list, trial,
                          **info) -> None:
        pass

    def on_trial_error(self, iteration: int, trials: list, trial,
                       **info) -> None:
        pass

    def on_experiment_end(self, trials: list, **info) -> None:
        pass


def fire(callbacks, hook: str, *args, **kwargs) -> None:
    for cb in callbacks or ():
        try:
            getattr(cb, hook)(*args, **kwargs)
        except Exception:  # noqa: BLE001
            logger.exception("tune callback %s.%s failed",
                             type(cb).__name__, hook)
