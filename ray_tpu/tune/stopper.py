"""Programmatic trial stoppers (ray: python/ray/tune/stopper/).

A Stopper is callable per (trial_id, result) and can end the whole
experiment via stop_all(); `RunConfig(stop=...)` accepts one anywhere a
dict or callable is accepted (the controller's _should_stop treats the
instance as the callable it is).
"""
from __future__ import annotations

from collections import defaultdict, deque


class Stopper:
    def __call__(self, trial_id: str, result: dict) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class MaximumIterationStopper(Stopper):
    """ray: stopper/maximum_iteration.py."""

    def __init__(self, max_iter: int):
        self._max_iter = max_iter

    def __call__(self, trial_id: str, result: dict) -> bool:
        return result.get("training_iteration", 0) >= self._max_iter


class TrialPlateauStopper(Stopper):
    """Stop a trial whose metric stopped moving (ray:
    stopper/trial_plateau.py): std of the last `num_results` values
    under `std`, after at least `grace_period` results."""

    def __init__(self, metric: str, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4,
                 mode: str | None = None):
        self._metric = metric
        self._std = std
        self._num_results = num_results
        self._grace = grace_period
        self._window: dict[str, deque] = defaultdict(
            lambda: deque(maxlen=num_results))
        self._count: dict[str, int] = defaultdict(int)

    def __call__(self, trial_id: str, result: dict) -> bool:
        v = result.get(self._metric)
        if v is None:
            return False
        self._count[trial_id] += 1
        win = self._window[trial_id]
        win.append(float(v))
        if self._count[trial_id] < self._grace \
                or len(win) < self._num_results:
            return False
        mean = sum(win) / len(win)
        var = sum((x - mean) ** 2 for x in win) / len(win)
        return var ** 0.5 <= self._std


class CombinedStopper(Stopper):
    """ray: stopper/combined.py — OR over sub-stoppers."""

    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id: str, result: dict) -> bool:
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self) -> bool:
        return any(s.stop_all() for s in self._stoppers)
