"""Tuner / TuneConfig / ResultGrid: the public Tune surface.

Analog of ray: python/ray/tune/tuner.py:44 (Tuner, fit :344, restore),
tune/result_grid.py (ResultGrid), and the legacy `tune.run` entry point.
A Trainer passed as the trainable rides through `as_trainable()`
(ray: BaseTrainer.fit wraps itself in a 1-trial Tuner; here Tune wraps
Train — same coupling, inverted dependency).
"""
from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.experiment import ERROR, TERMINATED, ExperimentState, Trial
from ray_tpu.tune.schedulers import FIFOScheduler, TrialScheduler
from ray_tpu.tune.search.searcher import (BasicVariantGenerator,
                                          ConcurrencyLimiter, Searcher)
from ray_tpu.tune.trainable import (Trainable, is_trainable_class,
                                    wrap_function)
from ray_tpu.tune.tune_controller import TuneController


@dataclasses.dataclass
class TuneConfig:
    """ray: python/ray/tune/tune_config.py."""

    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Searcher | None = None
    scheduler: TrialScheduler | None = None
    seed: int | None = None
    max_failures: int = 0
    checkpoint_freq: int = 0


class Result:
    """One trial's outcome (ray: ray.train.Result as returned by tune)."""

    def __init__(self, trial: Trial):
        self.metrics = trial.last_result or {}
        self.metrics_history = list(trial.results)
        self.checkpoint = trial.checkpoint
        self.error = trial.error
        self.config = trial.config
        self.trial_id = trial.trial_id
        self.path = None

    def __repr__(self):
        return (f"Result(trial_id={self.trial_id}, metrics={self.metrics}, "
                f"error={self.error})")


class ResultGrid:
    """ray: python/ray/tune/result_grid.py."""

    def __init__(self, trials: list[Trial], metric: str | None,
                 mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self._results = [Result(t) for t in trials]

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> list[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: str | None = None,
                        mode: str | None = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric is required (set TuneConfig.metric)")
        scored = [r for r in self._results
                  if r.metrics and r.metrics.get(metric) is not None]
        if not scored:
            raise RuntimeError("no trial reported metric "
                               f"{metric!r}; errors: {self.errors}")
        key = lambda r: r.metrics[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored,
                                                              key=key)

    def get_dataframe(self) -> list[dict]:
        """Rows of final metrics + flattened config (list of dicts — a
        DataFrame without the pandas dependency)."""
        from ray_tpu.tune.search.variant_generator import flatten

        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row["trial_id"] = r.trial_id
            for k, v in flatten(r.config or {}).items():
                row[f"config/{k}"] = v
            rows.append(row)
        return rows


class Tuner:
    """ray: python/ray/tune/tuner.py:44."""

    def __init__(self, trainable: Any = None, *,
                 param_space: dict | None = None,
                 tune_config: TuneConfig | None = None,
                 run_config: RunConfig | None = None,
                 _restored_trials: list[Trial] | None = None):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restored_trials = _restored_trials

    # ------------------------------------------------------------ plumbing
    def _experiment_name(self) -> str:
        if self.run_config.name:
            return self.run_config.name
        name = getattr(self.trainable, "__name__", None) or \
            type(self.trainable).__name__
        return f"{name}_tune"

    def _storage(self) -> str:
        return self.run_config.storage_path or os.path.join(
            tempfile.gettempdir(), "ray_tpu_results")

    def _resolved(self):
        """The registered object when `trainable` is a registry name —
        resource declarations live on the OBJECT, not the name."""
        t = self.trainable
        if isinstance(t, str):
            from ray_tpu.tune.registry import get_trainable_cls

            t = get_trainable_cls(t)
        return t

    def _trainable_cls(self) -> type:
        t = self._resolved()
        if is_trainable_class(t):
            return t
        if callable(t) and not hasattr(t, "as_trainable"):
            return wrap_function(t)
        if hasattr(t, "as_trainable"):   # a Trainer instance
            return wrap_function(t.as_trainable())
        raise TypeError(f"not a trainable: {t!r}")

    def _searcher(self) -> Searcher:
        tc = self.tune_config
        if tc.search_alg is not None:
            alg = tc.search_alg
            alg.set_search_properties(tc.metric, tc.mode, self.param_space)
            if tc.max_concurrent_trials and not isinstance(
                    alg, (ConcurrencyLimiter, BasicVariantGenerator)):
                alg = ConcurrencyLimiter(alg, tc.max_concurrent_trials)
            return alg
        return BasicVariantGenerator(self.param_space,
                                     num_samples=tc.num_samples,
                                     seed=tc.seed, metric=tc.metric,
                                     mode=tc.mode)

    def _external_trial_cap(self) -> int:
        """num_samples bounds model-based searchers, which suggest
        forever; a BasicVariantGenerator (bare or concurrency-wrapped)
        self-limits via its own num_samples and must NOT be double
        capped.  0 = no external cap."""
        alg = self.tune_config.search_alg
        if alg is None:
            return 0
        inner = alg.searcher if isinstance(alg, ConcurrencyLimiter) else alg
        if isinstance(inner, BasicVariantGenerator):
            return 0
        return self.tune_config.num_samples

    def _resources(self):
        t = self._resolved()
        declared = getattr(t, "_tune_resources", None)
        if declared is not None:      # tune.with_resources / PGF
            return declared
        if hasattr(t, "scaling_config"):
            # Trainer: the trial actor only coordinates; its workers hold
            # the real resources (ray: _maybe_warn_resource_contention)
            return {"CPU": 0.1}
        return {"CPU": 1.0}

    # -------------------------------------------------------------- public
    def fit(self) -> ResultGrid:
        tc = self.tune_config
        controller = TuneController(
            self._trainable_cls(),
            searcher=self._searcher(),
            scheduler=tc.scheduler,
            metric=tc.metric, mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            storage_path=self._storage(),
            experiment_name=self._experiment_name(),
            stop=self.run_config.stop,
            max_failures=tc.max_failures,
            resources_per_trial=self._resources(),
            checkpoint_freq=tc.checkpoint_freq,
            num_samples=self._external_trial_cap(),
            restored_trials=self._restored_trials,
            callbacks=self.run_config.callbacks)
        trials = controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)

    @classmethod
    def can_restore(cls, path: str) -> bool:
        return os.path.exists(os.path.join(path, "experiment_state.json"))

    @classmethod
    def restore(cls, path: str, trainable: Any,
                resume_errored: bool = False) -> "Tuner":
        """Resume an interrupted experiment from its storage dir
        (ray: Tuner.restore tuner.py): finished trials keep results,
        unfinished ones restart (from checkpoint when present)."""
        path = path.rstrip("/")
        storage, name = os.path.split(path)
        state = ExperimentState(storage, name)
        trials, meta = state.load(name)
        for t in trials:
            if t.status in (TERMINATED,):
                continue
            if t.status == ERROR and not resume_errored:
                continue
            t.status = "PENDING"
            t.error = None
            t.num_failures = 0
        tuner = cls(trainable,
                    tune_config=TuneConfig(metric=meta.get("metric"),
                                           mode=meta.get("mode", "max"),
                                           num_samples=0),
                    run_config=RunConfig(name=name, storage_path=storage),
                    _restored_trials=trials)
        return tuner


def run(trainable, *, config: dict | None = None, num_samples: int = 1,
        metric: str | None = None, mode: str = "max",
        scheduler: TrialScheduler | None = None,
        search_alg: Searcher | None = None,
        stop: dict | None = None, storage_path: str | None = None,
        name: str | None = None, max_concurrent_trials: int = 0,
        **_ignored) -> ResultGrid:
    """Legacy entry point (ray: tune.run tune/tune.py)."""
    tuner = Tuner(
        trainable, param_space=config,
        tune_config=TuneConfig(metric=metric, mode=mode,
                               num_samples=num_samples,
                               scheduler=scheduler, search_alg=search_alg,
                               max_concurrent_trials=max_concurrent_trials),
        run_config=RunConfig(name=name, storage_path=storage_path,
                             stop=stop))
    return tuner.fit()
