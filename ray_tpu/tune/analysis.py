"""Experiment definition, post-hoc analysis, and legacy run_experiments
(ray: tune/experiment/experiment.py, tune/analysis/experiment_analysis.py,
tune/tune.py run_experiments).
"""
from __future__ import annotations

import os
from typing import Any, Callable

from ray_tpu.tune.experiment import ExperimentState, Trial


class TuneError(Exception):
    """ray: tune/error.py TuneError."""


class Experiment:
    """Declarative experiment spec consumed by run_experiments (ray:
    Experiment).  A thin record: Tuner is the primary API."""

    def __init__(self, name: str, run: Any, *, config: dict | None = None,
                 stop: Any = None, num_samples: int = 1,
                 storage_path: str | None = None,
                 resources_per_trial: dict | None = None):
        self.name = name
        self.run_identifier = run
        self.config = config or {}
        self.stop = stop
        self.num_samples = num_samples
        self.storage_path = storage_path
        self.resources_per_trial = resources_per_trial


def run_experiments(
        experiments: "Experiment | list[Experiment]") -> list[Trial]:
    """Sequentially run Experiment specs (ray: run_experiments); each
    rides the modern Tuner path."""
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune.trainable import with_resources
    from ray_tpu.tune.tuner import TuneConfig, Tuner

    if isinstance(experiments, Experiment):
        experiments = [experiments]
    trials: list[Trial] = []
    for exp in experiments:
        trainable = exp.run_identifier
        if exp.resources_per_trial:
            trainable = with_resources(trainable, exp.resources_per_trial)
        tuner = Tuner(
            trainable, param_space=exp.config,
            tune_config=TuneConfig(num_samples=exp.num_samples),
            run_config=RunConfig(name=exp.name, stop=exp.stop,
                                 storage_path=exp.storage_path))
        grid = tuner.fit()
        trials.extend(grid._trials)
    return trials


class ExperimentAnalysis:
    """Post-hoc view over a finished (or running) experiment's snapshot
    (ray: ExperimentAnalysis).  Loads experiment_state.json written by
    the controller."""

    def __init__(self, experiment_checkpoint_path: str,
                 default_metric: str | None = None,
                 default_mode: str | None = None):
        path = experiment_checkpoint_path
        if os.path.isfile(path):
            path = os.path.dirname(path)
        storage, name = os.path.split(path.rstrip("/"))
        self._state = ExperimentState(storage, name)
        self.trials, self._meta = self._state.load(name)
        self.default_metric = default_metric or self._meta.get("metric")
        self.default_mode = default_mode or self._meta.get("mode", "max")

    def _scored(self, metric: str) -> list[Trial]:
        return [t for t in self.trials
                if t.last_result and t.last_result.get(metric) is not None]

    def get_best_trial(self, metric: str | None = None,
                       mode: str | None = None) -> Trial | None:
        metric = metric or self.default_metric
        mode = mode or self.default_mode
        scored = self._scored(metric)
        if not scored:
            return None
        key: Callable = lambda t: t.last_result[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored,
                                                              key=key)

    @property
    def best_trial(self) -> Trial | None:
        return self.get_best_trial()

    @property
    def best_config(self) -> dict | None:
        t = self.get_best_trial()
        return t.config if t else None

    @property
    def best_checkpoint(self):
        t = self.get_best_trial()
        return t.checkpoint if t else None

    def dataframe(self) -> list[dict]:
        """Final-result rows (list of dicts, pandas-free)."""
        out = []
        for t in self.trials:
            row = dict(t.last_result or {})
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            out.append(row)
        return out
