"""Trainable / env registry (ray: python/ray/tune/registry.py).

Names registered in the driver resolve in Tuner(trainable="name") and
rl Algorithm(env="name").  The registry is process-local: trainables
ship to trial actors by value (cloudpickle), exactly like unregistered
ones, so no cluster-side table is needed (the reference's GCS-backed
registry exists to serve its separate-process trainable resolution).
"""
from __future__ import annotations

from typing import Any, Callable

_trainables: dict[str, Any] = {}


def register_trainable(name: str, trainable: Any) -> None:
    if not callable(trainable):
        raise TypeError(f"trainable must be callable, got {trainable!r}")
    _trainables[name] = trainable


def get_trainable_cls(name: str) -> Any:
    if name not in _trainables:
        raise ValueError(f"unknown trainable {name!r}; "
                         f"registered: {sorted(_trainables)}")
    return _trainables[name]


def register_env(name: str, env_creator: Callable) -> None:
    """Delegates to the rl env registry — tune.register_env and the
    rllib registry are one table in the reference too."""
    from ray_tpu.rl.env import register_env as _register

    _register(name, env_creator)
