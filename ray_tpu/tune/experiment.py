"""Trial bookkeeping + experiment state persistence.

Analog of ray: python/ray/tune/experiment/trial.py and
tune/execution/experiment_state.py — the controller snapshots every trial
(config, status, results, checkpoint path) to `experiment_state.json` in
the run's storage dir; `Tuner.restore` resumes unfinished trials from it.
"""
from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    def __init__(self, trial_id: str | None, config: dict,
                 experiment_name: str = "exp",
                 resources: dict | None = None):
        self.trial_id = trial_id or uuid.uuid4().hex[:8]
        self.config = config
        self.experiment_name = experiment_name
        self.resources = resources or {"CPU": 1.0}
        self.status = PENDING
        self.last_result: dict | None = None
        self.results: list[dict] = []
        self.checkpoint: Checkpoint | None = None
        self.error: str | None = None
        self.num_failures = 0
        self.start_time: float | None = None
        # set when PBT replaces the config before a restart
        self.restore_config: dict | None = None

    @property
    def name(self) -> str:
        return f"{self.experiment_name}_{self.trial_id}"

    def metric_value(self, metric: str, mode: str = "max") -> float:
        vals = [r[metric] for r in self.results
                if r.get(metric) is not None]
        if not vals:
            return float("-inf") if mode == "max" else float("inf")
        return max(vals) if mode == "max" else min(vals)

    def to_json(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": _jsonable(self.config),
            "status": self.status,
            "last_result": _jsonable(self.last_result),
            "num_results": len(self.results),
            "checkpoint_path": self.checkpoint.path if self.checkpoint
            else None,
            "error": self.error,
            "num_failures": self.num_failures,
        }

    @classmethod
    def from_json(cls, d: dict, experiment_name: str) -> "Trial":
        t = cls(d["trial_id"], d.get("config") or {}, experiment_name)
        t.status = d["status"]
        t.last_result = d.get("last_result")
        if t.last_result:
            t.results = [t.last_result]
        if d.get("checkpoint_path") and os.path.exists(d["checkpoint_path"]):
            t.checkpoint = Checkpoint(d["checkpoint_path"])
        t.error = d.get("error")
        return t

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


def _jsonable(obj: Any) -> Any:
    try:
        json.dumps(obj)
        return obj
    except (TypeError, ValueError):
        if isinstance(obj, dict):
            return {str(k): _jsonable(v) for k, v in obj.items()}
        return repr(obj)


class ExperimentState:
    """Periodic JSON snapshots enabling Tuner.restore."""

    def __init__(self, storage_path: str, name: str):
        self.dir = os.path.join(storage_path, name)
        os.makedirs(self.dir, exist_ok=True)
        self.path = os.path.join(self.dir, "experiment_state.json")

    def save(self, trials: list[Trial], metadata: dict | None = None) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"ts": time.time(),
                       "metadata": metadata or {},
                       "trials": [t.to_json() for t in trials]}, f, indent=1)
        os.replace(tmp, self.path)

    def load(self, experiment_name: str) -> tuple[list[Trial], dict]:
        with open(self.path) as f:
            data = json.load(f)
        trials = [Trial.from_json(d, experiment_name)
                  for d in data["trials"]]
        return trials, data.get("metadata", {})

    @staticmethod
    def exists(storage_path: str, name: str) -> bool:
        return os.path.exists(
            os.path.join(storage_path, name, "experiment_state.json"))
