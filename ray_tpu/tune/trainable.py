"""Trainable: the unit of execution Tune schedules.

Analog of ray: python/ray/tune/trainable/trainable.py (class API:
setup/step/save_checkpoint/load_checkpoint) + function_trainable.py
(a user function running in a thread, reporting via tune.report; each
`train()` call returns the next reported result).  The controller runs
one Trainable per trial as an actor and calls train() repeatedly — pause
and PBT exploitation are checkpoint save/restore on actor boundaries.
"""
from __future__ import annotations

import os
import queue
import tempfile
import threading
import time
import traceback
from typing import Any, Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint

RESULT_DONE = "__trial_done__"          # marker key in a final result
TRAINING_ITERATION = "training_iteration"

_fn_session: Optional["_FnSession"] = None


class Trainable:
    """Class API: subclass, override setup/step/save_checkpoint/
    load_checkpoint; Tune calls train() per iteration."""

    def __init__(self, config: dict | None = None):
        self.config = config or {}
        self._iteration = 0
        self._start = time.time()
        self.setup(self.config)

    # ----------------------------------------------------------- user hooks
    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Reuse this instance for a new config (PBT explore without an
        actor restart).  Return False to force a restart."""
        return False

    # ------------------------------------------------------- controller API
    def train(self) -> dict:
        result = self.step()
        self._iteration += 1
        result.setdefault(TRAINING_ITERATION, self._iteration)
        result.setdefault("time_total_s", time.time() - self._start)
        result.setdefault("trial_id", getattr(self, "trial_id", ""))
        return result

    def save(self) -> Checkpoint:
        d = tempfile.mkdtemp(prefix="tune-ckpt-")
        self.save_checkpoint(d)
        self._write_meta(d)
        return Checkpoint(d)

    def restore(self, checkpoint: Checkpoint) -> None:
        self._read_meta(checkpoint.path)
        self.load_checkpoint(checkpoint.path)

    def stop(self) -> None:
        self.cleanup()

    def _write_meta(self, d: str) -> None:
        import json

        with open(os.path.join(d, ".tune_metadata"), "w") as f:
            json.dump({"iteration": self._iteration}, f)

    def _read_meta(self, d: str) -> None:
        import json

        p = os.path.join(d, ".tune_metadata")
        if os.path.exists(p):
            with open(p) as f:
                self._iteration = json.load(f)["iteration"]


class _FnSession:
    """Per-function-trial session backing tune.report/get_checkpoint."""

    def __init__(self, checkpoint: Checkpoint | None):
        self.results: queue.Queue = queue.Queue(maxsize=2)
        self.continue_sem = threading.Semaphore(0)
        self.loaded_checkpoint = checkpoint
        self.stop_event = threading.Event()
        self.last_checkpoint: Checkpoint | None = None

    def report(self, metrics: dict, checkpoint: Checkpoint | None) -> None:
        if self.stop_event.is_set():
            raise StopIteration("trial stopped by the tune controller")
        self.last_checkpoint = checkpoint
        self.results.put({"metrics": dict(metrics), "checkpoint": checkpoint})
        # block until the controller consumed the result: keeps function
        # trainables in lock-step with scheduling decisions (ray: function
        # trainables block in session.report until train() is called again)
        self.continue_sem.acquire()
        if self.stop_event.is_set():
            raise StopIteration("trial stopped by the tune controller")


def report(metrics: dict, checkpoint: Checkpoint | None = None) -> None:
    """tune.report — valid inside a function trainable (or train worker
    when called under Train; train.report takes precedence there)."""
    if _fn_session is None:
        raise RuntimeError("tune.report called outside a tune trial")
    _fn_session.report(metrics, checkpoint)


def get_checkpoint() -> Checkpoint | None:
    if _fn_session is None:
        return None
    return _fn_session.loaded_checkpoint


class FunctionTrainable(Trainable):
    """Wraps fn(config) in a thread; each train() returns the next
    tune.report'ed result (ray: tune/trainable/function_trainable.py)."""

    _fn: Callable = None  # set by wrap_function subclassing

    def setup(self, config: dict) -> None:
        self._session: _FnSession | None = None
        self._thread: threading.Thread | None = None
        self._error: list[str] = []
        self._fn_done = threading.Event()
        self._resume_ckpt: Checkpoint | None = None
        self._ret: Any = None

    def _ensure_started(self) -> None:
        if self._thread is not None:
            return
        global _fn_session
        self._session = _FnSession(self._resume_ckpt)
        _fn_session = self._session

        def runner():
            try:
                self._ret = type(self)._fn(self.config)
            except StopIteration:
                pass
            except BaseException:  # noqa: BLE001
                self._error.append(traceback.format_exc())
            finally:
                self._fn_done.set()
                self._session.results.put(None)   # wake a blocked train()

        self._thread = threading.Thread(target=runner, daemon=True,
                                        name="tune-fn")
        self._thread.start()

    def step(self) -> dict:
        self._ensure_started()
        # release the fn thread blocked in report() for the PREVIOUS result:
        # between train() calls the thread sits at the report barrier, so a
        # pause/save sees a quiescent function (ray's session semantics).
        if getattr(self, "_consumed_one", False):
            self._session.continue_sem.release()
        self._consumed_one = True
        while True:
            try:
                item = self._session.results.get(timeout=0.5)
                break
            except queue.Empty:
                if self._fn_done.is_set() and self._session.results.empty():
                    item = None
                    break
        if item is None:
            if self._error:
                raise RuntimeError(
                    f"trial function failed:\n{self._error[0]}")
            out = dict(self._ret) if isinstance(self._ret, dict) else {}
            out[RESULT_DONE] = True
            return out
        metrics = item["metrics"]
        self._last_fn_checkpoint = item.get("checkpoint")
        return metrics

    def resume_training(self) -> None:
        """Unblock the fn thread after the controller consumed a result."""
        if self._session is not None:
            self._session.continue_sem.release()

    def save_checkpoint(self, checkpoint_dir: str) -> None:
        ckpt = getattr(self, "_last_fn_checkpoint", None) or \
            (self._session.last_checkpoint if self._session else None)
        if ckpt is not None:
            import shutil

            for name in os.listdir(ckpt.path):
                src = os.path.join(ckpt.path, name)
                dst = os.path.join(checkpoint_dir, name)
                if os.path.isdir(src):
                    shutil.copytree(src, dst, dirs_exist_ok=True)
                else:
                    shutil.copy2(src, dst)

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        self._resume_ckpt = Checkpoint(checkpoint_dir)

    def cleanup(self) -> None:
        if self._session is not None:
            self._session.stop_event.set()
            self._session.continue_sem.release()
            if self._thread is not None:
                self._thread.join(timeout=2.0)


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass bound to `fn`."""
    return type(f"fn_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})


def is_trainable_class(obj: Any) -> bool:
    return isinstance(obj, type) and issubclass(obj, Trainable)


def with_parameters(trainable: Any, **kwargs: Any) -> Any:
    """Bind large objects to a trainable via the object store (ray:
    tune.with_parameters): each value is put() ONCE and every trial
    fetches the shared copy instead of re-pickling it into each trial's
    config/closure."""
    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    if isinstance(trainable, type):
        if not issubclass(trainable, Trainable):
            raise TypeError("with_parameters expects a function or a "
                            "Trainable subclass")

        class _WithParams(trainable):
            def setup(self, config: dict) -> None:
                super().setup(
                    {**config,
                     **{k: ray_tpu.get(r) for k, r in refs.items()}})

        _WithParams.__name__ = trainable.__name__
        _WithParams._tune_with_parameters = True
        return _WithParams

    fn = trainable

    def _bound(config: dict):
        return fn(config,
                  **{k: ray_tpu.get(r) for k, r in refs.items()})

    _bound.__name__ = getattr(fn, "__name__", "trainable")
    return _bound


def with_resources(trainable: Any, resources: Any) -> Any:
    """Attach a per-trial resource request (ray: tune.with_resources).
    `resources` is a dict ({"CPU": 2}) or a PlacementGroupFactory."""
    if isinstance(trainable, type):
        out = type(trainable.__name__, (trainable,), {})
    elif callable(trainable):
        def out(config):  # noqa: ANN001
            return trainable(config)

        out.__name__ = getattr(trainable, "__name__", "trainable")
    else:
        raise TypeError(f"not a trainable: {trainable!r}")
    out._tune_resources = resources
    return out
