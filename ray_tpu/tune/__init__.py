"""ray_tpu.tune: hyperparameter tuning over trial actors.

Capability analog of ray: python/ray/tune — Tuner.fit drives N trials
(each a Trainable in its own actor) through searchers (grid/random/TPE)
and schedulers (ASHA, PBT, median-stopping), with checkpoint-carrying
pause/resume and experiment-state restore.
"""
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     HyperBandScheduler, MedianStoppingRule,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.search.sample import (choice, grid_search, lograndint,
                                        loguniform, qloguniform, qrandint,
                                        quniform, randint, randn,
                                        sample_from, uniform)
from ray_tpu.tune.search.searcher import (BasicVariantGenerator,
                                          ConcurrencyLimiter, Searcher)
from ray_tpu.tune.search.bohb import BOHBSearch
from ray_tpu.tune.search.tpe import TPESearch
from ray_tpu.tune.trainable import (Trainable, get_checkpoint, report,
                                    wrap_function)
from ray_tpu.tune.tuner import (Result, ResultGrid, TuneConfig, Tuner, run)

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Result", "run",
    "Trainable", "report", "get_checkpoint", "wrap_function",
    "Searcher", "BasicVariantGenerator", "ConcurrencyLimiter", "TPESearch", "BOHBSearch",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining",
    "uniform", "quniform", "loguniform", "qloguniform", "randn", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "grid_search",
]
