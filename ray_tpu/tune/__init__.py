"""ray_tpu.tune: hyperparameter tuning over trial actors.

Capability analog of ray: python/ray/tune — Tuner.fit drives N trials
(each a Trainable in its own actor) through searchers (grid/random/TPE)
and schedulers (ASHA, PBT, median-stopping), with checkpoint-carrying
pause/resume and experiment-state restore.
"""
from ray_tpu.tune.schedulers import (AsyncHyperBandScheduler, FIFOScheduler,
                                     HyperBandScheduler, MedianStoppingRule,
                                     PopulationBasedTraining, TrialScheduler)
from ray_tpu.tune.search.sample import (choice, grid_search, lograndint,
                                        loguniform, qloguniform, qrandint,
                                        quniform, randint, randn,
                                        sample_from, uniform)
from ray_tpu.tune.search.searcher import (BasicVariantGenerator,
                                          ConcurrencyLimiter, Searcher)
from ray_tpu.tune.search.bohb import BOHBSearch
from ray_tpu.tune.search.tpe import TPESearch
from ray_tpu.tune.search.sample import qlograndint, qrandn
from ray_tpu.tune.analysis import (Experiment, ExperimentAnalysis,
                                   TuneError, run_experiments)
from ray_tpu.tune.callback import Callback
from ray_tpu.tune.placement_groups import PlacementGroupFactory
from ray_tpu.tune.progress_reporter import (CLIReporter,
                                            JupyterNotebookReporter,
                                            ProgressReporter)
from ray_tpu.tune.registry import register_env, register_trainable
from ray_tpu.tune.stopper import (CombinedStopper,
                                  MaximumIterationStopper, Stopper,
                                  TrialPlateauStopper)
from ray_tpu.tune.trainable import (Trainable, get_checkpoint, report,
                                    with_parameters, with_resources,
                                    wrap_function)
from ray_tpu.tune.tuner import (Result, ResultGrid, TuneConfig, Tuner, run)

ASHAScheduler = AsyncHyperBandScheduler


def create_scheduler(name: str, **kwargs):
    """Scheduler by name (ray: tune/schedulers/__init__.py
    create_scheduler)."""
    table = {"fifo": FIFOScheduler, "asha": AsyncHyperBandScheduler,
             "async_hyperband": AsyncHyperBandScheduler,
             "hyperband": HyperBandScheduler,
             "median_stopping_rule": MedianStoppingRule,
             "pbt": PopulationBasedTraining}
    if name not in table:
        raise ValueError(f"unknown scheduler {name!r}: {sorted(table)}")
    return table[name](**kwargs)


def create_searcher(name: str, **kwargs):
    """Searcher by name (ray: tune/search/__init__.py
    create_searcher)."""
    table = {"random": BasicVariantGenerator,
             "variant_generator": BasicVariantGenerator,
             "hyperopt": TPESearch, "tpe": TPESearch, "bohb": BOHBSearch}
    if name not in table:
        raise ValueError(f"unknown searcher {name!r}: {sorted(table)}")
    return table[name](**kwargs)

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Result", "run",
    "Trainable", "report", "get_checkpoint", "wrap_function",
    "Searcher", "BasicVariantGenerator", "ConcurrencyLimiter", "TPESearch", "BOHBSearch",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining",
    "uniform", "quniform", "loguniform", "qloguniform", "randn", "randint",
    "qrandint", "lograndint", "qlograndint", "qrandn", "choice",
    "sample_from", "grid_search",
    "Stopper", "CombinedStopper", "MaximumIterationStopper",
    "TrialPlateauStopper", "Callback", "ProgressReporter", "CLIReporter",
    "JupyterNotebookReporter", "PlacementGroupFactory", "TuneError",
    "Experiment", "ExperimentAnalysis", "run_experiments",
    "register_trainable", "register_env", "with_parameters",
    "with_resources", "create_scheduler", "create_searcher",
]
