"""BOHBSearch: budget-aware Bayesian optimization (BOHB-style).

Capability analog of ray's TuneBOHB integration (ray:
python/ray/tune/search/bohb/bohb_search.py, which wraps hpbandster) with
no external dependency.  The BOHB recipe (Falkner et al. 2018): pair a
HyperBand-style scheduler with a TPE model built PER BUDGET — when
suggesting, use the largest budget (training_iteration) that has enough
observations, so early-rung results guide sampling while late-rung
results dominate once available.

Pair with tune.schedulers.HyperBandScheduler/AsyncHyperBandScheduler —
intermediate results are observed via on_trial_result, so trials stopped
at a rung still contribute their last score at that budget.
"""
from __future__ import annotations

from typing import Optional

from ray_tpu.tune.search.tpe import TPESearch


class BOHBSearch(TPESearch):
    def __init__(self, *args, min_points_per_budget: int = 4, **kwargs):
        super().__init__(*args, **kwargs)
        self._min_pts = min_points_per_budget
        # trial_id -> {budget: score}; budget = training_iteration.
        self._by_budget: dict[str, dict[int, float]] = {}

    # -------------------------------------------------------- observations
    def on_trial_result(self, trial_id: str, result: dict) -> None:
        if self.metric not in result:
            return
        budget = int(result.get("training_iteration", 1))
        self._by_budget.setdefault(trial_id, {})[budget] = \
            float(result[self.metric])

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        if error:
            self._by_budget.pop(trial_id, None)
            self._points.pop(trial_id, None)
            return
        if result and self.metric in result:
            self.on_trial_result(trial_id, result)

    def suggest(self, trial_id: str):
        # Lazy re-score: suggest() is the only consumer of the per-budget
        # scores, so the O(trials × budgets) refresh runs once per new
        # trial, not once per reported result.
        self._refresh_scores()
        return super().suggest(trial_id)

    def _refresh_scores(self) -> None:
        """Re-score every observed point at the modeling budget: the
        largest budget with >= min_points observations (smaller budgets
        back-fill trials that never reached it)."""
        budgets: dict[int, int] = {}
        for scores in self._by_budget.values():
            for b in scores:
                budgets[b] = budgets.get(b, 0) + 1
        eligible = [b for b, n in budgets.items() if n >= self._min_pts]
        model_budget = max(eligible) if eligible else \
            (max(budgets) if budgets else 1)
        for tid, scores in self._by_budget.items():
            if tid not in self._points:
                continue
            pt, _ = self._points[tid]
            # Score at the modeling budget, else the trial's largest
            # smaller budget (its best-known performance).
            at = [b for b in scores if b <= model_budget]
            if not at:
                continue
            self._points[tid] = (pt, scores[max(at)])


__all__ = ["BOHBSearch"]
