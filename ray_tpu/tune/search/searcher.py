"""Searcher interface + BasicVariantGenerator + ConcurrencyLimiter.

Analog of ray: python/ray/tune/search/searcher.py, basic_variant.py,
concurrency_limiter.py.  A Searcher suggests configs for new trials and
observes results; the controller owns trial lifecycle.
"""
from __future__ import annotations

import random
from typing import Any, Optional

from ray_tpu.tune.search.sample import Domain, GridSearch
from ray_tpu.tune.search.variant_generator import (count_grid_variants,
                                                   generate_variants)

FINISHED = "FINISHED"   # sentinel: search space exhausted


class Searcher:
    def __init__(self, metric: str | None = None, mode: str = "max"):
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: str | None, mode: str | None,
                              config: dict) -> bool:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[dict]:
        """A concrete config, None (wait: nothing to suggest yet), or
        FINISHED."""
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: dict | None = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples, domains sampled randomly
    (ray: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict | None = None, num_samples: int = 1,
                 seed: int | None = None, **kwargs):
        super().__init__(**kwargs)
        self._space = param_space or {}
        self._num_samples = num_samples
        self._rng = random.Random(seed)
        self._iter = None
        self._round = 0

    def set_search_properties(self, metric, mode, config) -> bool:
        if config:
            self._space = config
        return super().set_search_properties(metric, mode, config)

    @property
    def total_trials(self) -> int:
        return count_grid_variants(self._space) * self._num_samples

    def suggest(self, trial_id: str) -> Optional[dict]:
        while True:
            if self._iter is None:
                if self._round >= self._num_samples:
                    return FINISHED
                self._iter = generate_variants(self._space, self._rng)
                self._round += 1
            try:
                return next(self._iter)
            except StopIteration:
                self._iter = None


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggestions (ray: tune/search/concurrency_limiter.py).
    Essential for sequential model-based searchers like TPE."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher.metric, searcher.mode)
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        return self.searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if len(self._live) >= self.max_concurrent:
            return None
        out = self.searcher.suggest(trial_id)
        if out is not None and out != FINISHED:
            self._live.add(trial_id)
        return out

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


def has_unresolved_values(spec: Any) -> bool:
    if isinstance(spec, dict):
        return any(has_unresolved_values(v) for v in spec.values())
    return isinstance(spec, (Domain, GridSearch))
