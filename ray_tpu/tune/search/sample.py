"""Search-space domains: tune.uniform / loguniform / choice / grid_search.

Analog of ray: python/ray/tune/search/sample.py (Domain/Float/Integer/
Categorical) and variant_generator.py's grid_search marker.  Domains are
plain samplable descriptions; the variant generator and searchers resolve
them into concrete configs.
"""
from __future__ import annotations

import random
from typing import Any, Callable, Sequence


class Domain:
    """A samplable parameter range."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError

    # Bounds for searchers that model the space (TPE, PBT perturbation).
    lower: float | None = None
    upper: float | None = None
    is_log: bool = False
    is_int: bool = False


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: float | None = None):
        if log and lower <= 0:
            raise ValueError("loguniform lower bound must be > 0")
        self.lower, self.upper, self.is_log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.is_log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(round(v / self.q) * self.q, 10)
        return min(max(v, self.lower), self.upper)

    def __repr__(self):
        k = "loguniform" if self.is_log else "uniform"
        return f"{k}({self.lower}, {self.upper})"


class Integer(Domain):
    is_int = True

    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: int = 1):
        self.lower, self.upper, self.is_log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        if self.is_log:
            import math

            v = int(math.exp(rng.uniform(math.log(max(self.lower, 1)),
                                         math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1) \
                if self.upper > self.lower else self.lower
        if self.upper > self.lower:
            v = min(max(v, self.lower), self.upper - 1)
        else:
            v = self.lower
        if self.q > 1:
            # Round to q LAST, then snap back inside the (q-aligned)
            # range — clamping after rounding could return non-multiples
            # of q (e.g. upper-1) to the searcher.
            v = int(round(v / self.q) * self.q)
            if v > self.upper - 1:
                v -= self.q
            if v < self.lower:
                v += self.q
            # When q exceeds the range width no q-multiple may fit; a
            # single +/-q correction can still land outside [lower,
            # upper-1] (round-4 advisor finding).  Hard-clamp as the
            # final word: in-range beats q-aligned.
            v = min(max(v, self.lower), self.upper - 1)
        return v

    def __repr__(self):
        return f"randint({self.lower}, {self.upper})"


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)

    def __repr__(self):
        return f"choice({self.categories})"


class Normal(Domain):
    def __init__(self, mean: float = 0.0, sd: float = 1.0,
                 q: float | None = None):
        self.mean, self.sd, self.q = mean, sd, q

    def sample(self, rng: random.Random) -> float:
        v = rng.gauss(self.mean, self.sd)
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Function(Domain):
    """tune.sample_from — arbitrary callable over the partial config spec."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        try:
            return self.fn(None)
        except TypeError:
            return self.fn()


class GridSearch:
    """Marker for exhaustive expansion (ray: tune.grid_search)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)

    def __repr__(self):
        return f"grid_search({self.values})"


# ------------------------------------------------------------- public API
def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def qrandn(mean: float, sd: float, q: float) -> Normal:
    return Normal(mean, sd, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int = 1) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def qlograndint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, log=True, q=q)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)
