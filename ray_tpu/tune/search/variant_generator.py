"""Expand a param_space into concrete trial configs.

Analog of ray: python/ray/tune/search/variant_generator.py — grid_search
entries form a cross product; Domain entries are sampled per variant;
nested dicts are traversed recursively.
"""
from __future__ import annotations

import itertools
import random
from typing import Any, Iterator

from ray_tpu.tune.search.sample import Domain, GridSearch


def _walk(spec: Any, path: tuple = ()) -> Iterator[tuple[tuple, Any]]:
    if isinstance(spec, dict):
        for k, v in spec.items():
            yield from _walk(v, path + (k,))
    else:
        yield path, spec


def _assign(config: dict, path: tuple, value: Any) -> None:
    d = config
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def count_grid_variants(spec: dict) -> int:
    n = 1
    for _, v in _walk(spec):
        if isinstance(v, GridSearch):
            n *= len(v.values)
    return n


def generate_variants(spec: dict, rng: random.Random) -> Iterator[dict]:
    """Yield one concrete config per grid cross-product element, sampling
    every Domain leaf independently per variant."""
    grid_paths = [(p, v.values) for p, v in _walk(spec)
                  if isinstance(v, GridSearch)]
    combos = itertools.product(*[vals for _, vals in grid_paths]) \
        if grid_paths else [()]
    for combo in combos:
        config: dict = {}
        grid_at = {p: val for (p, _), val in zip(grid_paths, combo)}
        for path, v in _walk(spec):
            if isinstance(v, GridSearch):
                _assign(config, path, grid_at[path])
            elif isinstance(v, Domain):
                _assign(config, path, v.sample(rng))
            else:
                _assign(config, path, v)
        yield config


def flatten(config: dict, prefix: str = "") -> dict:
    """Flatten nested config to dotted keys (for searchers/dataframes)."""
    out = {}
    for k, v in config.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten(v, key + "/"))
        else:
            out[key] = v
    return out
