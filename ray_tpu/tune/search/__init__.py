from ray_tpu.tune.search.sample import (choice, grid_search, lograndint,
                                        loguniform, qloguniform, qrandint,
                                        quniform, randint, randn,
                                        sample_from, uniform)
from ray_tpu.tune.search.searcher import (BasicVariantGenerator,
                                          ConcurrencyLimiter, Searcher)
from ray_tpu.tune.search.bohb import BOHBSearch
from ray_tpu.tune.search.tpe import TPESearch
from ray_tpu.tune.search.variant_generator import (flatten,
                                                   generate_variants)

__all__ = [
    "uniform", "quniform", "loguniform", "qloguniform", "randn", "randint",
    "qrandint", "lograndint", "choice", "sample_from", "grid_search",
    "Searcher", "BasicVariantGenerator", "ConcurrencyLimiter", "TPESearch", "BOHBSearch",
    "generate_variants", "flatten",
]
