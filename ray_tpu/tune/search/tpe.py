"""TPESearch: native Tree-structured Parzen Estimator searcher.

Capability analog of ray's hyperopt/optuna integrations (ray:
python/ray/tune/search/hyperopt/hyperopt_search.py) with no external
dependency: the classic TPE split — divide observed trials into good/bad
by quantile gamma, model each set with a Parzen (Gaussian-kernel) mixture
per dimension, and pick the candidate maximising l(x)/g(x).  Categorical
dims use smoothed empirical frequencies.  Falls back to random sampling
until `n_initial_points` results exist.
"""
from __future__ import annotations

import math
import random
from typing import Any, Optional

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.variant_generator import _assign, _walk


class TPESearch(Searcher):
    def __init__(self, space: dict | None = None, metric: str | None = None,
                 mode: str = "max", n_initial_points: int = 8,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: int | None = None):
        super().__init__(metric, mode)
        self._space = space or {}
        self._n_init = n_initial_points
        self._gamma = gamma
        self._n_cand = n_candidates
        self._rng = random.Random(seed)
        # trial_id -> (flat point dict, score or None)
        self._points: dict[str, tuple[dict, float | None]] = {}

    def set_search_properties(self, metric, mode, config) -> bool:
        if config:
            self._space = config
        return super().set_search_properties(metric, mode, config)

    # ------------------------------------------------------------ modeling
    def _dims(self) -> list[tuple[tuple, Domain]]:
        return [(p, v) for p, v in _walk(self._space)
                if isinstance(v, Domain)]

    def _observed(self) -> list[tuple[dict, float]]:
        return [(pt, s) for pt, s in self._points.values() if s is not None]

    def _to_unit(self, dom: Domain, v: float) -> float:
        lo, hi = dom.lower, dom.upper
        if dom.is_log:
            return (math.log(v) - math.log(lo)) / \
                (math.log(hi) - math.log(lo) + 1e-12)
        return (v - lo) / (hi - lo + 1e-12)

    def _parzen_logpdf(self, xs: list[float], x: float) -> float:
        if not xs:
            return 0.0
        bw = max(1.0 / max(len(xs), 1) ** 0.5 * 0.5, 0.05)
        acc = 0.0
        for c in xs:
            acc += math.exp(-0.5 * ((x - c) / bw) ** 2)
        return math.log(acc / len(xs) / (bw * math.sqrt(2 * math.pi)) + 1e-12)

    def _suggest_dim(self, dom: Domain, good: list[Any],
                     bad: list[Any]) -> Any:
        if isinstance(dom, Categorical):
            # smoothed frequency ratio over categories
            def score(cat):
                g = (good.count(cat) + 1) / (len(good) + len(dom.categories))
                b = (bad.count(cat) + 1) / (len(bad) + len(dom.categories))
                return g / b
            cands = [dom.sample(self._rng) for _ in range(self._n_cand)]
            return max(cands, key=score)
        gu = [self._to_unit(dom, v) for v in good]
        bu = [self._to_unit(dom, v) for v in bad]
        best_v, best_s = None, -math.inf
        for _ in range(self._n_cand):
            v = dom.sample(self._rng)
            u = self._to_unit(dom, v)
            s = self._parzen_logpdf(gu, u) - self._parzen_logpdf(bu, u)
            if s > best_s:
                best_v, best_s = v, s
        return best_v

    # ------------------------------------------------------------ Searcher
    def suggest(self, trial_id: str) -> Optional[dict]:
        dims = self._dims()
        config: dict = {}
        for path, v in _walk(self._space):
            if not isinstance(v, Domain):
                _assign(config, path, v)
        obs = self._observed()
        if len(obs) < self._n_init:
            for path, dom in dims:
                _assign(config, path, dom.sample(self._rng))
        else:
            obs.sort(key=lambda o: o[1], reverse=(self.mode == "max"))
            n_good = max(1, int(len(obs) * self._gamma))
            good_pts = [o[0] for o in obs[:n_good]]
            bad_pts = [o[0] for o in obs[n_good:]] or good_pts
            for path, dom in dims:
                key = "/".join(map(str, path))
                good = [p[key] for p in good_pts if key in p]
                bad = [p[key] for p in bad_pts if key in p]
                _assign(config, path, self._suggest_dim(dom, good, bad))
        flat = {"/".join(map(str, p)): _get(config, p) for p, _ in dims}
        self._points[trial_id] = (flat, None)
        return config

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        if trial_id not in self._points:
            return
        if error or not result or self.metric not in result:
            self._points.pop(trial_id, None)
            return
        pt, _ = self._points[trial_id]
        self._points[trial_id] = (pt, float(result[self.metric]))


def _get(config: dict, path: tuple) -> Any:
    d = config
    for k in path:
        d = d[k]
    return d
