"""Progress reporting (ray: python/ray/tune/progress_reporter.py).

Redesigned as a Callback (the reference drives reporters from its own
loop; riding the callback hooks gives the same output without a second
dispatch path).  CLIReporter prints a throttled status table.
"""
from __future__ import annotations

import sys
import time

from ray_tpu.tune.callback import Callback


class ProgressReporter(Callback):
    pass


class CLIReporter(ProgressReporter):
    def __init__(self, *, metric_columns: list[str] | None = None,
                 max_report_frequency: float = 5.0, out=None):
        self._metrics = metric_columns
        self._period = max_report_frequency
        self._last = 0.0
        self._out = out or sys.stdout

    def _row(self, t) -> str:
        r = t.last_result or {}
        metrics = self._metrics or [k for k in r
                                    if isinstance(r[k], (int, float))][:4]
        cells = " ".join(f"{m}={r.get(m)}" for m in metrics)
        return f"  {t.trial_id} {t.status:<10} it={len(t.results)} {cells}"

    def _print(self, trials, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last < self._period:
            return
        self._last = now
        by = {}
        for t in trials:
            by[t.status] = by.get(t.status, 0) + 1
        head = ", ".join(f"{v} {k}" for k, v in sorted(by.items()))
        print(f"== Tune status: {head} ==", file=self._out)
        for t in trials:
            print(self._row(t), file=self._out)

    def on_trial_result(self, iteration, trials, trial, result, **info):
        self._print(trials)

    def on_trial_complete(self, iteration, trials, trial, **info):
        self._print(trials)

    def on_experiment_end(self, trials, **info):
        self._print(trials, force=True)


# Notebook environments get the same text output (the reference's rich
# HTML table is a frontend nicety, not behavior).
JupyterNotebookReporter = CLIReporter
