"""Per-trial placement-group resource requests (ray:
tune/execution/placement_groups.py PlacementGroupFactory).

A trial requesting a PlacementGroupFactory gets a placement group with
those bundles created before its actor starts; the trial actor lands in
bundle 0 and the PG is removed when the trial's actor stops.
"""
from __future__ import annotations


class PlacementGroupFactory:
    def __init__(self, bundles: list[dict], strategy: str = "PACK"):
        if not bundles:
            raise ValueError("PlacementGroupFactory needs >= 1 bundle")
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy

    @property
    def required_resources(self) -> dict:
        out: dict = {}
        for b in self.bundles:
            for k, v in b.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def __repr__(self):
        return (f"PlacementGroupFactory({self.bundles}, "
                f"strategy={self.strategy!r})")
