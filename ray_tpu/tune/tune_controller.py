"""TuneController: the trial-driving event loop.

Analog of ray: python/ray/tune/execution/tune_controller.py:68 — an event
loop over trial actors (one actor per running trial, resources reserved
via actor options), feeding every result to the scheduler + searcher and
enforcing their decisions (CONTINUE / PAUSE / STOP).  Pause and PBT
exploitation move checkpoints across actor restarts.  State snapshots to
`experiment_state.json` after every transition enable restore.
"""
from __future__ import annotations

import logging
import time
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.experiment import (ERROR, PAUSED, PENDING, RUNNING,
                                     TERMINATED, ExperimentState, Trial)
from ray_tpu.tune.schedulers import (CONTINUE, PAUSE, STOP, FIFOScheduler,
                                     TrialScheduler)
from ray_tpu.tune.search.searcher import FINISHED, Searcher
from ray_tpu.tune.trainable import RESULT_DONE, TRAINING_ITERATION

logger = logging.getLogger(__name__)


class _TrialRunner:
    """In-actor host for one Trainable instance."""

    def __init__(self, trainable_cls: type, config: dict, trial_id: str,
                 checkpoint: Checkpoint | None = None):
        self._t = trainable_cls(dict(config))
        self._t.trial_id = trial_id
        if checkpoint is not None:
            self._t.restore(checkpoint)

    def train(self) -> dict:
        return self._t.train()

    def save(self) -> Checkpoint:
        return self._t.save()

    def stop(self) -> None:
        self._t.stop()

    def reset(self, new_config: dict) -> bool:
        ok = self._t.reset_config(dict(new_config))
        if ok:
            self._t.config = dict(new_config)
        return bool(ok)


class TuneController:
    def __init__(self, trainable_cls: type, *,
                 searcher: Searcher,
                 scheduler: TrialScheduler | None = None,
                 metric: str | None = None, mode: str = "max",
                 max_concurrent: int = 0,
                 storage_path: str, experiment_name: str,
                 stop: dict | Callable | None = None,
                 max_failures: int = 0,
                 resources_per_trial: dict | None = None,
                 checkpoint_freq: int = 0,
                 num_samples: int = 0,
                 restored_trials: list[Trial] | None = None,
                 callbacks: list | None = None):
        self.trainable_cls = trainable_cls
        self.searcher = searcher
        # Trial budget for model-based searchers, which suggest forever
        # (ray: num_samples bounds any search_alg, not just the basic
        # variant generator).  0 = unbounded (grid searchers self-end).
        self.num_samples = num_samples
        self.scheduler = scheduler or FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.max_concurrent = max_concurrent
        self.stop_criteria = stop
        self.max_failures = max_failures
        self.resources = resources_per_trial or {"CPU": 1.0}
        self.checkpoint_freq = checkpoint_freq
        self.callbacks = list(callbacks or [])
        self._iteration = 0
        self._stop_all = False
        # trial_id -> PlacementGroup for PlacementGroupFactory trials
        self._trial_pgs: dict = {}
        self.experiment_name = experiment_name
        self.state = ExperimentState(storage_path, experiment_name)

        self.trials: list[Trial] = list(restored_trials or [])
        self._actors: dict[str, Any] = {}          # trial_id -> handle
        self._futures: dict[Any, str] = {}         # train() ref -> trial_id
        self._search_done = False
        self.scheduler.set_search_properties(metric, mode)
        for t in self.trials:
            self.scheduler.on_trial_add(t)

    # -------------------------------------------------------------- helpers
    def _live(self) -> list[Trial]:
        return [t for t in self.trials if t.status in (PENDING, RUNNING,
                                                       PAUSED)]

    def _running(self) -> list[Trial]:
        return [t for t in self.trials if t.status == RUNNING]

    def _next_from_search(self) -> Optional[Trial]:
        if self._search_done:
            return None
        if self.num_samples and len(self.trials) >= self.num_samples:
            self._search_done = True
            return None
        tid = f"{len(self.trials):05d}"
        out = self.searcher.suggest(tid)
        if out == FINISHED:
            self._search_done = True
            return None
        if out is None:
            return None
        trial = Trial(tid, out, self.experiment_name,
                      resources=self.resources)
        self.trials.append(trial)
        self.scheduler.on_trial_add(trial)
        return trial

    def _start_trial(self, trial: Trial) -> None:
        checkpoint = trial.checkpoint
        config = trial.config
        if trial.status == PAUSED and isinstance(
                self.scheduler, sched_mod.PopulationBasedTraining):
            exploited = self.scheduler.exploit(trial, self.trials)
            if exploited is not None:
                donor, new_config = exploited
                ckpt = self._donor_checkpoint(donor)
                if ckpt is not None:
                    checkpoint = ckpt
                    config = new_config
                    trial.config = new_config
        from ray_tpu.tune.placement_groups import PlacementGroupFactory

        if isinstance(trial.resources, PlacementGroupFactory):
            # The trial gets its own PG; the runner actor rides bundle 0
            # (ray: trials schedule inside their PlacementGroupFactory
            # reservation; worker groups started by trainers consume the
            # other bundles).
            from ray_tpu.utils.placement_group import placement_group

            pg = self._trial_pgs.get(trial.trial_id)
            if pg is None:
                pg = placement_group(trial.resources.bundles,
                                     strategy=trial.resources.strategy)
                if not pg.ready(timeout=60.0):
                    # Unreservable now: don't launch against unplaced
                    # bundles — fail the trial visibly (step()'s except
                    # path records the error and releases the PG).
                    from ray_tpu.utils.placement_group import \
                        remove_placement_group

                    remove_placement_group(pg)
                    raise RuntimeError(
                        f"placement group for trial {trial.trial_id} "
                        f"not ready in 60s: {trial.resources}")
                self._trial_pgs[trial.trial_id] = pg
            opts = {"placement_group": pg,
                    "placement_group_bundle_index": 0}
        else:
            opts = _actor_options(trial.resources)
        runner = ray_tpu.remote(_TrialRunner).options(**opts).remote(
            self.trainable_cls, config, trial.trial_id, checkpoint)
        self._actors[trial.trial_id] = runner
        trial.status = RUNNING
        trial.start_time = trial.start_time or time.time()
        from ray_tpu.tune.callback import fire

        fire(self.callbacks, "on_trial_start", self._iteration,
             self.trials, trial)
        self._submit_train(trial)

    def _donor_checkpoint(self, donor: Trial) -> Checkpoint | None:
        """Latest checkpoint of a (possibly running) donor trial."""
        handle = self._actors.get(donor.trial_id)
        if handle is not None:
            try:
                return ray_tpu.get(handle.save.remote(), timeout=60.0)
            except Exception:  # noqa: BLE001
                pass
        return donor.checkpoint

    def _submit_train(self, trial: Trial) -> None:
        ref = self._actors[trial.trial_id].train.remote()
        self._futures[ref] = trial.trial_id

    def _stop_actor(self, trial: Trial, save: bool = False) -> None:
        handle = self._actors.pop(trial.trial_id, None)
        if handle is not None:
            try:
                if save:
                    trial.checkpoint = ray_tpu.get(handle.save.remote(),
                                                   timeout=60.0)
                ray_tpu.get(handle.stop.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001
                pass
            ray_tpu.kill(handle)
        pg = self._trial_pgs.pop(trial.trial_id, None)
        if pg is not None:
            from ray_tpu.utils.placement_group import \
                remove_placement_group

            try:
                remove_placement_group(pg)
            except Exception:  # noqa: BLE001
                pass

    def _should_stop(self, trial: Trial, result: dict) -> bool:
        crit = self.stop_criteria
        if crit is None:
            return False
        if callable(crit):
            hit = bool(crit(trial.trial_id, result))
            # A Stopper can end the whole experiment (ray: stop_all()
            # polled after each result).
            if getattr(crit, "stop_all", None) and crit.stop_all():
                self._stop_all = True
            return hit
        for key, bound in crit.items():
            v = result.get(key)
            if v is not None and v >= bound:
                return True
        return False

    # ------------------------------------------------------------ main loop
    def step(self) -> bool:
        """One scheduling step; returns False when the experiment is done."""
        self._iteration += 1
        if self._stop_all:
            for t in self._running():
                self._complete(t, TERMINATED)
            return False
        # 1. launch work up to the concurrency cap
        cap = self.max_concurrent or 10 ** 9
        while len(self._running()) < cap:
            trial = self.scheduler.choose_trial_to_run(
                [t for t in self.trials if t.status in (PENDING, PAUSED)])
            if trial is None:
                trial = self._next_from_search()
            if trial is None:
                break
            try:
                self._start_trial(trial)
            except Exception as e:  # noqa: BLE001
                trial.status = ERROR
                trial.error = repr(e)
                self.scheduler.on_trial_complete(trial, trial.last_result)
                self.searcher.on_trial_complete(trial.trial_id, error=True)
                from ray_tpu.tune.callback import fire

                fire(self.callbacks, "on_trial_error", self._iteration,
                     self.trials, trial)
        if not self._futures:
            if self._live():
                time.sleep(0.05)   # searcher momentarily out of suggestions
                return True
            return False

        # 2. wait for any train() result
        ready, _ = ray_tpu.wait(list(self._futures), num_returns=1,
                                timeout=5.0)
        for ref in ready:
            trial_id = self._futures.pop(ref)
            trial = next(t for t in self.trials if t.trial_id == trial_id)
            try:
                result = ray_tpu.get(ref)
            except Exception as e:  # noqa: BLE001
                self._on_trial_error(trial, e)
                continue
            self._on_trial_result(trial, result)
        self.state.save(self.trials, {"metric": self.metric,
                                      "mode": self.mode})
        return bool(self._live() or self._futures)

    _AUTO_KEYS = frozenset({TRAINING_ITERATION, "time_total_s", "trial_id"})

    def _on_trial_result(self, trial: Trial, result: dict) -> None:
        from ray_tpu.tune.callback import fire

        if not result.get(RESULT_DONE):
            fire(self.callbacks, "on_trial_result", self._iteration,
                 self.trials, trial, result)
        if result.pop(RESULT_DONE, False):
            # the done marker only carries data when the fn returned a dict
            if set(result) - self._AUTO_KEYS:
                trial.results.append(result)
                trial.last_result = result
            self._complete(trial, TERMINATED)
            return
        trial.results.append(result)
        trial.last_result = result
        self.searcher.on_trial_result(trial.trial_id, result)
        decision = self.scheduler.on_trial_result(trial, result)
        if self._should_stop(trial, result):
            decision = STOP
        if decision == CONTINUE:
            it = result.get(TRAINING_ITERATION, 0)
            if self.checkpoint_freq and it % self.checkpoint_freq == 0:
                handle = self._actors[trial.trial_id]
                try:
                    trial.checkpoint = ray_tpu.get(handle.save.remote(),
                                                   timeout=60.0)
                except Exception:  # noqa: BLE001
                    pass
            self._submit_train(trial)
        elif decision == PAUSE:
            self._stop_actor(trial, save=True)
            trial.status = PAUSED
        elif decision == STOP:
            self._complete(trial, TERMINATED)

    def _on_trial_error(self, trial: Trial, err: Exception) -> None:
        trial.num_failures += 1
        logger.warning("trial %s failed (%d): %r", trial.trial_id,
                       trial.num_failures, err)
        self._stop_actor(trial)
        if trial.num_failures <= self.max_failures:
            trial.status = PENDING   # retried from last checkpoint
            return
        trial.status = ERROR
        trial.error = repr(err)
        self.scheduler.on_trial_complete(trial, trial.last_result)
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result,
                                        error=True)
        from ray_tpu.tune.callback import fire

        fire(self.callbacks, "on_trial_error", self._iteration,
             self.trials, trial)

    def _complete(self, trial: Trial, status: str) -> None:
        self._stop_actor(trial, save=trial.checkpoint is None)
        trial.status = status
        self.scheduler.on_trial_complete(trial, trial.last_result)
        self.searcher.on_trial_complete(trial.trial_id, trial.last_result)
        from ray_tpu.tune.callback import fire

        fire(self.callbacks,
             "on_trial_error" if status == ERROR else "on_trial_complete",
             self._iteration, self.trials, trial)

    def run(self) -> list[Trial]:
        try:
            while self.step():
                pass
        finally:
            for t in self._running():
                self._stop_actor(t)
                if t.status == RUNNING:
                    t.status = TERMINATED
            self.state.save(self.trials, {"metric": self.metric,
                                          "mode": self.mode})
            from ray_tpu.tune.callback import fire

            fire(self.callbacks, "on_experiment_end", self.trials)
        return self.trials


def _actor_options(resources: dict) -> dict:
    opts: dict = {}
    r = dict(resources)
    if "CPU" in r:
        opts["num_cpus"] = r.pop("CPU")
    if "TPU" in r:
        opts["num_tpus"] = r.pop("TPU")
    if r:
        opts["resources"] = r
    return opts
