"""Trial schedulers: FIFO, ASHA, median-stopping, PBT, HyperBand.

Analog of ray: python/ray/tune/schedulers/ (async_hyperband.py ASHA,
median_stopping_rule.py, pbt.py).  A scheduler sees every result and
returns a decision; the controller enforces it.  PBT additionally mutates
paused trials' configs and transplants checkpoints (exploit/explore).
"""
from __future__ import annotations

import math
import random
from typing import Any, Callable, Optional

# decisions
CONTINUE = "CONTINUE"
PAUSE = "PAUSE"
STOP = "STOP"


class TrialScheduler:
    def set_search_properties(self, metric: str | None,
                              mode: str | None) -> None:
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode

    metric: str | None = None
    mode: str = "max"

    def on_trial_add(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, trial, result: dict | None) -> None:
        pass

    def choose_trial_to_run(self, trials: list) -> Optional[Any]:
        """Pick the next PENDING/PAUSED trial to (re)start, or None."""
        for t in trials:
            if t.status == "PENDING":
                return t
        for t in trials:
            if t.status == "PAUSED":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (ray: tune/schedulers/async_hyperband.py): rungs at
    grace_period * reduction_factor^k; at each rung a trial continues only
    if its metric is in the top 1/reduction_factor of results recorded at
    that rung.  Asynchronous: decisions never wait for stragglers."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str | None = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4, brackets: int = 1):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung value -> list of recorded metric scores (sign-normalised)
        self._brackets: list[dict[float, list[float]]] = [
            {} for _ in range(max(brackets, 1))]
        self._trial_bracket: dict[str, int] = {}
        self._rng = random.Random(0)

    def _rungs(self, bracket: int) -> list[float]:
        rungs = []
        t = self.grace * (self.rf ** bracket)
        while t < self.max_t:
            rungs.append(t)
            t *= self.rf
        return rungs

    def on_trial_add(self, trial) -> None:
        self._trial_bracket[trial.trial_id] = \
            self._rng.randrange(len(self._brackets))

    def on_trial_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr)
        v = result.get(self.metric)
        if t is None or v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        sign = 1.0 if self.mode == "max" else -1.0
        score = sign * float(v)
        b = self._trial_bracket.get(trial.trial_id, 0)
        rung_scores = self._brackets[b]
        decision = CONTINUE
        for rung in sorted(self._rungs(b), reverse=True):
            if t < rung:
                continue
            recorded = rung_scores.setdefault(rung, [])
            # record once per trial per rung
            key = (trial.trial_id, rung)
            if key not in getattr(self, "_seen", set()):
                self._seen = getattr(self, "_seen", set())
                self._seen.add(key)
                recorded.append(score)
            if len(recorded) >= self.rf:
                cutoff = _quantile(recorded, 1.0 - 1.0 / self.rf)
                if score < cutoff:
                    decision = STOP
            break  # only the highest reached rung gates
        return decision


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of
    other trials' running means at the same step (ray:
    tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str | None = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: dict[str, list[float]] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        sign = 1.0 if self.mode == "max" else -1.0
        self._history.setdefault(trial.trial_id, []).append(sign * float(v))
        if t < self.grace:
            return CONTINUE
        means = [sum(h) / len(h) for tid, h in self._history.items()
                 if tid != trial.trial_id and h]
        if len(means) < self.min_samples:
            return CONTINUE
        my_best = max(self._history[trial.trial_id])
        if my_best < _quantile(means, 0.5):
            return STOP
        return CONTINUE


class HyperBandScheduler(AsyncHyperBandScheduler):
    """Synchronous HyperBand approximated by multi-bracket ASHA — the
    asynchronous variant dominates in practice (ray ships both; ASHA is
    the recommended default, ray: tune/schedulers/__init__.py)."""

    def __init__(self, **kwargs):
        kwargs.setdefault("brackets", 3)
        super().__init__(**kwargs)


class PopulationBasedTraining(TrialScheduler):
    """PBT (ray: tune/schedulers/pbt.py): every perturbation_interval,
    bottom-quantile trials PAUSE; on restart the controller calls
    `exploit(trial)` which clones a top-quantile trial's checkpoint and
    perturbs its hyperparameters (resample with prob 0.25, else ×1.2 or
    ×0.8 for numeric; next/prev for categorical)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: str | None = None, mode: str = "max",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: dict | None = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: int | None = None):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: dict[str, float] = {}
        self._scores: dict[str, float] = {}

    def on_trial_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        v = result.get(self.metric)
        if v is not None:
            sign = 1.0 if self.mode == "max" else -1.0
            self._scores[trial.trial_id] = sign * float(v)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        if len(self._scores) < 2:
            return CONTINUE
        ranked = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        if trial.trial_id in bottom:
            return PAUSE   # controller will exploit+explore on resume
        return CONTINUE

    # ------------------------------------------------------- exploit/explore
    def exploit(self, trial, trials: list) -> tuple[Any, dict] | None:
        """Pick a top-quantile donor; return (donor_trial, mutated config)
        or None if no donor is available."""
        ranked = sorted(
            (t for t in trials
             if t.trial_id in self._scores and t.trial_id != trial.trial_id),
            key=lambda t: self._scores[t.trial_id], reverse=True)
        if not ranked:
            return None
        k = max(1, int(len(ranked) * self.quantile))
        donor = self._rng.choice(ranked[:k])
        new_config = dict(donor.config)
        for key, spec in self.mutations.items():
            cur = new_config.get(key)
            new_config[key] = self._mutate(key, cur, spec)
        return donor, new_config

    def _mutate(self, key: str, cur: Any, spec: Any) -> Any:
        from ray_tpu.tune.search.sample import Domain

        resample = cur is None or self._rng.random() < self.resample_prob
        if isinstance(spec, Domain):
            if resample:
                return spec.sample(self._rng)
            factor = 1.2 if self._rng.random() > 0.5 else 0.8
            v = cur * factor
            if spec.lower is not None:
                v = min(max(v, spec.lower), spec.upper)
            return int(v) if spec.is_int else v
        if isinstance(spec, (list, tuple)):
            if resample or cur not in spec:
                return self._rng.choice(list(spec))
            i = list(spec).index(cur)
            j = min(max(i + self._rng.choice([-1, 1]), 0), len(spec) - 1)
            return spec[j]
        if callable(spec):
            return spec()
        return cur


def _quantile(xs: list[float], q: float) -> float:
    s = sorted(xs)
    if not s:
        return -math.inf
    idx = min(int(q * len(s)), len(s) - 1)
    return s[idx]
