"""ray_tpu: a TPU-native distributed compute framework.

Provides the capability surface of the reference framework (tasks, actors,
objects, placement groups, data/train/tune/serve/rl libraries) re-designed
TPU-first: XLA collectives over ICI inside a slice, a zmq control/object
plane over DCN between hosts, jax/pjit/Pallas for all device compute.
"""
from ray_tpu.api import (available_resources, cancel, cluster_resources, get,
                         get_actor, init, is_initialized, kill, method,
                         nodes, put, remote, shutdown, timeline, wait)
from ray_tpu.exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                                ObjectLostError, RayTpuError,
                                TaskCancelledError, TaskError,
                                WorkerCrashedError)
from ray_tpu._private import profiling
from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get",
    "put", "wait", "kill", "cancel", "get_actor", "nodes", "timeline",
    "available_resources", "cluster_resources", "get_runtime_context",
    "profiling",
    "ObjectRef", "ObjectRefGenerator",
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "ObjectLostError", "GetTimeoutError", "TaskCancelledError",
    "WorkerCrashedError", "__version__",
]
