"""ray_tpu: a TPU-native distributed compute framework.

Provides the capability surface of the reference framework (tasks, actors,
objects, placement groups, data/train/tune/serve/rl libraries) re-designed
TPU-first: XLA collectives over ICI inside a slice, a zmq control/object
plane over DCN between hosts, jax/pjit/Pallas for all device compute.
"""
from ray_tpu.api import (LOCAL_MODE, SCRIPT_MODE, WORKER_MODE,
                         ClientBuilder, Language, available_resources,
                         cancel, cluster_resources, cpp_function, get,
                         get_actor, get_gpu_ids, get_tpu_ids, init,
                         is_initialized, kill, method, nodes, put, remote,
                         show_in_dashboard, shutdown, timeline, wait)
from ray_tpu.exceptions import (ActorDiedError, ActorError, GetTimeoutError,
                                ObjectLostError, RayTpuError,
                                TaskCancelledError, TaskError,
                                WorkerCrashedError)
from ray_tpu._private import profiling
from ray_tpu.logging_config import LoggingConfig
from ray_tpu.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get",
    "put", "wait", "kill", "cancel", "get_actor", "nodes", "timeline",
    "available_resources", "cluster_resources", "get_runtime_context",
    "profiling", "LoggingConfig", "ClientBuilder", "Language",
    "cpp_function", "get_gpu_ids", "get_tpu_ids", "show_in_dashboard",
    "SCRIPT_MODE", "WORKER_MODE", "LOCAL_MODE",
    "ObjectRef", "ObjectRefGenerator",
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "ObjectLostError", "GetTimeoutError", "TaskCancelledError",
    "WorkerCrashedError", "__version__",
]


def __getattr__(name):
    # Submodules reachable as attributes without import-time cost (ray:
    # ray.autoscaler / ray.client are importable off the top level).
    if name in ("autoscaler", "client", "data", "train", "tune", "serve",
                "rl", "workflow", "dag", "experimental", "utils",
                "cluster_utils", "failpoints", "tracing", "telemetry",
                "memledger"):
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
