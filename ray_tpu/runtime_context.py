"""Runtime context: introspection of the current worker/task/actor.

Analog of ray: python/ray/runtime_context.py.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class RuntimeContext:
    job_id: str
    node_id: str
    worker_id: str
    actor_id: str | None
    task_id: str | None
    namespace: str

    def get_node_id(self) -> str:
        return self.node_id

    def get_actor_id(self) -> str | None:
        return self.actor_id

    def get_task_id(self) -> str | None:
        return self.task_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_worker_id(self) -> str:
        return self.worker_id


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    return RuntimeContext(
        job_id=core.job_id,
        node_id=core.node_id,
        worker_id=core.worker_id,
        actor_id=core.current_actor_id,
        task_id=core.current_task_id,
        namespace=core.namespace,
    )
