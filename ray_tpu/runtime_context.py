"""Runtime context: introspection of the current worker/task/actor.

Analog of ray: python/ray/runtime_context.py.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class RuntimeContext:
    job_id: str
    node_id: str
    worker_id: str
    actor_id: str | None
    task_id: str | None
    namespace: str
    trace_context: dict | None = None
    controller_address: str = ""
    assigned_resources: dict | None = None
    runtime_env: dict | None = None

    def get_node_id(self) -> str:
        return self.node_id

    def get_actor_id(self) -> str | None:
        return self.actor_id

    def get_task_id(self) -> str | None:
        return self.task_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_trace_context(self) -> dict | None:
        """The executing task's trace context — {"trace_id",
        "parent_span", "span_id"} — propagated automatically through
        nested task/actor submissions (ray: OpenTelemetry propagation,
        util/tracing/tracing_helper.py); None on the driver."""
        return self.trace_context

    # ------------------------------------------- reference-surface extras
    def get(self) -> dict:
        """Legacy dict form (ray: RuntimeContext.get)."""
        out = {"job_id": self.job_id, "node_id": self.node_id,
               "namespace": self.namespace}
        if self.actor_id:
            out["actor_id"] = self.actor_id
        if self.task_id:
            out["task_id"] = self.task_id
        return out

    @property
    def gcs_address(self) -> str:
        """The controller address (the GCS analog)."""
        return self.controller_address

    def get_placement_group_id(self) -> str | None:
        """PG id of the current task/actor, or None (ray:
        get_placement_group_id)."""
        from ray_tpu.utils.placement_group import \
            get_current_placement_group

        pg = get_current_placement_group()
        return pg.id if pg else None

    def get_actor_name(self) -> str | None:
        """Name of the current actor when it has one (ray:
        get_actor_name)."""
        if not self.actor_id:
            return None
        from ray_tpu._private.worker import global_worker

        core = global_worker()
        reply, _ = core.call(core.controller_addr, "list_actors",
                             timeout=30.0)
        for a in reply["actors"]:
            if a["actor_id"] == self.actor_id:
                return a.get("name")
        return None

    def get_assigned_resources(self) -> dict:
        """Resources of the current task/actor lease (ray:
        get_assigned_resources)."""
        return dict(self.assigned_resources or {})

    def get_accelerator_ids(self) -> dict:
        """{"TPU": [...]} chip ids visible to this worker (ray:
        get_accelerator_ids — GPU/TPU/... keyed; only TPU exists
        here)."""
        from ray_tpu.api import get_tpu_ids

        return {"TPU": [str(i) for i in get_tpu_ids()]}

    def get_runtime_env_string(self) -> str:
        import json as _json

        return _json.dumps(self.runtime_env or {})


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    return RuntimeContext(
        job_id=core.job_id,
        node_id=core.node_id,
        worker_id=core.worker_id,
        actor_id=core.current_actor_id,
        task_id=core.current_task_id,
        namespace=core.namespace,
        trace_context=core.current_trace,
        controller_address=core.controller_addr,
        assigned_resources=getattr(core, "current_resources", None),
        runtime_env=getattr(core, "current_runtime_env", None),
    )
