"""Runtime context: introspection of the current worker/task/actor.

Analog of ray: python/ray/runtime_context.py.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class RuntimeContext:
    job_id: str
    node_id: str
    worker_id: str
    actor_id: str | None
    task_id: str | None
    namespace: str
    trace_context: dict | None = None

    def get_node_id(self) -> str:
        return self.node_id

    def get_actor_id(self) -> str | None:
        return self.actor_id

    def get_task_id(self) -> str | None:
        return self.task_id

    def get_job_id(self) -> str:
        return self.job_id

    def get_worker_id(self) -> str:
        return self.worker_id

    def get_trace_context(self) -> dict | None:
        """The executing task's trace context — {"trace_id",
        "parent_span", "span_id"} — propagated automatically through
        nested task/actor submissions (ray: OpenTelemetry propagation,
        util/tracing/tracing_helper.py); None on the driver."""
        return self.trace_context


def get_runtime_context() -> RuntimeContext:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    return RuntimeContext(
        job_id=core.job_id,
        node_id=core.node_id,
        worker_id=core.worker_id,
        actor_id=core.current_actor_id,
        task_id=core.current_task_id,
        namespace=core.namespace,
        trace_context=core.current_trace,
    )
