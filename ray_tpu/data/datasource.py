"""Datasources: read tasks that produce blocks, write helpers.

Analog of ray: python/ray/data/datasource/ (parquet/csv/json/... over
pyarrow.fs).  A ReadTask is a zero-arg callable returning an iterator of
blocks; the planner turns each into one ray_tpu task so reads parallelize
and stream like any other operator.
"""
from __future__ import annotations

import glob as globmod
import os
from typing import Any, Callable, Iterable, Iterator

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, _rows_to_table, _to_table

ReadTask = Callable[[], Iterator[Block]]


def _expand_paths(paths: str | list[str], suffix: str | None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(globmod.glob(pat)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


# ------------------------------------------------------------------ reads
def range_tasks(n: int, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    sizes = [n // parallelism + (1 if i < n % parallelism else 0)
             for i in range(parallelism)]
    tasks, start = [], 0
    for sz in sizes:
        s, e = start, start + sz

        def read(s=s, e=e) -> Iterator[Block]:
            yield pa.table({"id": np.arange(s, e, dtype=np.int64)})

        tasks.append(read)
        start = e
    return tasks


def items_tasks(items: list, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + parallelism - 1) // parallelism
    tasks = []
    for i in range(0, len(items), chunk):
        part = items[i:i + chunk]

        def read(part=part) -> Iterator[Block]:
            yield _rows_to_table(part)

        tasks.append(read)
    return tasks


def parquet_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".parquet")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        yield pq.read_table(path)

    return [lambda p=p: one(p) for p in files]


def csv_tasks(paths, parallelism: int, **opts) -> list[ReadTask]:
    files = _expand_paths(paths, ".csv")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.csv as pcsv

        yield pcsv.read_csv(path)

    return [lambda p=p: one(p) for p in files]


def json_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".json")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.json as pjson

        yield pjson.read_json(path)

    return [lambda p=p: one(p) for p in files]


def text_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": lines})

    return [lambda p=p: one(p) for p in files]


def numpy_tasks(arrays: list[np.ndarray], column: str = "data",
                ) -> list[ReadTask]:
    tasks = []
    for arr in arrays:
        def read(arr=arr) -> Iterator[Block]:
            yield _to_table({column: arr})

        tasks.append(read)
    return tasks


def generator_tasks(fns: list[Callable[[], Iterable[Any]]]) -> list[ReadTask]:
    """Custom per-shard generators (streaming token pipelines)."""
    def wrap(fn):
        def read() -> Iterator[Block]:
            for chunk in fn():
                yield _to_table(chunk) if not isinstance(chunk, pa.Table) \
                    else chunk

        return read

    return [wrap(fn) for fn in fns]


def image_tasks(paths, parallelism: int, size: tuple | None = None,
                mode: str | None = None) -> list[ReadTask]:
    """Image files → {"image": [h, w, c] uint8 ndarray, "path": str}
    rows (ray: data/datasource/image_datasource.py; PIL decode)."""
    files = _expand_paths(paths, None)
    files = [f for f in files if f.lower().endswith(
        (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff", ".webp"))] \
        or files

    def one(path: str) -> Iterator[Block]:
        from PIL import Image

        img = Image.open(path)
        if mode:
            img = img.convert(mode)
        if size:
            img = img.resize(size)
        arr = np.asarray(img)
        # Arrow blocks carry tensors as flattened fixed-size lists; the
        # original shape rides alongside so consumers reshape exactly
        # (np.asarray(row["image"], np.uint8).reshape(row["shape"])).
        yield _to_table({"image": arr[None],
                         "shape": [list(arr.shape)],
                         "path": [path]})

    return [lambda p=p: one(p) for p in files]


def binary_tasks(paths, parallelism: int) -> list[ReadTask]:
    """Whole files as bytes → {"bytes", "path"} rows (ray:
    data/datasource/binary_datasource.py)."""
    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": [data], "path": [path]})

    return [lambda p=p: one(p) for p in files]


# TFRecord framing: u64le length, u32le masked-crc32c(length), payload,
# u32le masked-crc32c(payload).  crc32c implemented here (Castagnoli
# polynomial, table-driven) — no tensorflow/crc32c wheel in the env.
_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def tfrecord_tasks(paths, parallelism: int,
                   verify: bool = False) -> list[ReadTask]:
    """TFRecord files → one {"record": bytes} row per record (ray:
    data/datasource/tfrecords_datasource.py; raw records — Example proto
    parsing is the caller's map step, keeping TF out of the core).

    Length-header CRCs are always checked (8 bytes each — cheap, and
    they catch framing corruption).  verify=True also checks payload
    CRCs; that runs the pure-Python crc32c over every byte, so it is
    off by default (the reference skips payload verification too)."""
    import struct as _struct

    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        records = []
        with open(path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                (length,) = _struct.unpack("<Q", head)
                (len_crc,) = _struct.unpack("<I", f.read(4))
                if len_crc != _masked_crc(head):
                    raise ValueError(f"{path}: corrupt length crc")
                payload = f.read(length)
                (data_crc,) = _struct.unpack("<I", f.read(4))
                if verify and data_crc != _masked_crc(payload):
                    raise ValueError(f"{path}: corrupt record crc")
                records.append(payload)
        yield pa.table({"record": records})

    return [lambda p=p: one(p) for p in files]


def _write_tfrecord(block: Block, out: str) -> None:
    import struct as _struct

    acc_cols = block.column_names
    col = "record" if "record" in acc_cols else acc_cols[0]
    with open(out, "wb") as f:
        for v in block.column(col).to_pylist():
            payload = v if isinstance(v, bytes) else str(v).encode()
            head = _struct.pack("<Q", len(payload))
            f.write(head)
            f.write(_struct.pack("<I", _masked_crc(head)))
            f.write(payload)
            f.write(_struct.pack("<I", _masked_crc(payload)))


# ----------------------------------------------------------------- writes
def write_block(block: Block, path: str, fmt: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, out)
    elif fmt == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(block, out)
    elif fmt == "json":
        block.to_pandas().to_json(out, orient="records", lines=True)
    elif fmt == "tfrecord":
        _write_tfrecord(block, out)
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return out


# ------------------------------------------------------------------- sql
def sql_tasks(sql: str, connection_factory: Callable[[], Any],
              parallelism: int = 1) -> list[ReadTask]:
    """DB-API query → rows (ray: data/_internal/datasource/sql_datasource
    .py — one task runs the query through a user connection factory;
    sqlite3 is the stdlib instance, any DB-API driver works)."""
    def read() -> Iterator[Block]:
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        yield _rows_to_table([dict(zip(cols, r)) for r in rows]) if rows \
            else pa.table({c: [] for c in cols})

    return [read]


def write_sql(block: Block, table: str,
              connection_factory: Callable[[], Any]) -> int:
    """INSERT one block (ray: Dataset.write_sql)."""
    cols = block.column_names
    rows = [tuple(r[c] for c in cols) for r in block.to_pylist()]
    conn = connection_factory()
    try:
        ph = ", ".join(["?"] * len(cols))
        conn.cursor().executemany(
            f"INSERT INTO {table} ({', '.join(cols)}) VALUES ({ph})", rows)
        conn.commit()
    finally:
        conn.close()
    return len(rows)


# ------------------------------------------------------------------ avro
# Minimal Avro Object Container File codec (spec: avro 1.11 binary
# encoding).  Pure python — no fastavro wheel in this environment; the
# reference wraps fastavro (data/_internal/datasource/avro_datasource.py)
# but the container format itself is ~100 lines: zigzag varints, a JSON
# schema in the header, deflate/null codecs, sync-marker-delimited blocks.
_AVRO_MAGIC = b"Obj\x01"


def _zz_read(buf, pos: int) -> tuple[int, int]:
    shift = acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


def _zz_write(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_decode(schema, buf, pos: int):
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(schema, list):                      # union
        idx, pos = _zz_read(buf, pos)
        return _avro_decode(schema[idx], buf, pos)
    if t in ("int", "long"):
        return _zz_read(buf, pos)
    if t == "null":
        return None, pos
    if t == "boolean":
        return bool(buf[pos]), pos + 1
    if t == "float":
        import struct as _s
        return _s.unpack_from("<f", buf, pos)[0], pos + 4
    if t == "double":
        import struct as _s
        return _s.unpack_from("<d", buf, pos)[0], pos + 8
    if t in ("bytes", "string"):
        n, pos = _zz_read(buf, pos)
        raw = bytes(buf[pos:pos + n])
        return (raw.decode() if t == "string" else raw), pos + n
    if t == "fixed":
        n = schema["size"]
        return bytes(buf[pos:pos + n]), pos + n
    if t == "enum":
        idx, pos = _zz_read(buf, pos)
        return schema["symbols"][idx], pos
    if t == "record":
        out = {}
        for f in schema["fields"]:
            out[f["name"]], pos = _avro_decode(f["type"], buf, pos)
        return out, pos
    if t == "array":
        items = []
        while True:
            n, pos = _zz_read(buf, pos)
            if n == 0:
                return items, pos
            if n < 0:                  # block with byte size prefix
                n = -n
                _, pos = _zz_read(buf, pos)
            for _ in range(n):
                v, pos = _avro_decode(schema["items"], buf, pos)
                items.append(v)
    if t == "map":
        out = {}
        while True:
            n, pos = _zz_read(buf, pos)
            if n == 0:
                return out, pos
            if n < 0:
                n = -n
                _, pos = _zz_read(buf, pos)
            for _ in range(n):
                k, pos = _avro_decode("string", buf, pos)
                out[k], pos = _avro_decode(schema["values"], buf, pos)
    raise ValueError(f"unsupported avro type {t!r}")


def _avro_encode(schema, value) -> bytes:
    t = schema["type"] if isinstance(schema, dict) else schema
    if isinstance(schema, list):
        for i, s in enumerate(schema):
            st = s["type"] if isinstance(s, dict) else s
            if (value is None) == (st == "null"):
                return _zz_write(i) + _avro_encode(s, value)
        raise ValueError("no union branch matched")
    if t in ("int", "long"):
        return _zz_write(int(value))
    if t == "null":
        return b""
    if t == "boolean":
        return bytes([1 if value else 0])
    if t == "float":
        import struct as _s
        return _s.pack("<f", value)
    if t == "double":
        import struct as _s
        return _s.pack("<d", value)
    if t == "string":
        raw = value.encode()
        return _zz_write(len(raw)) + raw
    if t == "bytes":
        return _zz_write(len(value)) + bytes(value)
    if t == "record":
        return b"".join(_avro_encode(f["type"], value[f["name"]])
                        for f in schema["fields"])
    if t == "array":
        out = b""
        if value:
            out += _zz_write(len(value))
            out += b"".join(_avro_encode(schema["items"], v)
                            for v in value)
        return out + _zz_write(0)
    raise ValueError(f"unsupported avro type for write {t!r}")


def avro_tasks(paths, parallelism: int) -> list[ReadTask]:
    """Avro container files → one row per record."""
    import json as _json
    import zlib

    files = _expand_paths(paths, ".avro")

    def one(path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            buf = f.read()
        if buf[:4] != _AVRO_MAGIC:
            raise ValueError(f"{path}: not an avro container file")
        meta, pos = _avro_decode(
            {"type": "map", "values": "bytes"}, buf, 4)
        schema = _json.loads(meta["avro.schema"])
        codec = meta.get("avro.codec", b"null").decode()
        sync = buf[pos:pos + 16]
        pos += 16
        rows = []
        while pos < len(buf):
            count, pos = _zz_read(buf, pos)
            size, pos = _zz_read(buf, pos)
            body = buf[pos:pos + size]
            pos += size
            if buf[pos:pos + 16] != sync:
                raise ValueError(f"{path}: sync marker mismatch")
            pos += 16
            if codec == "deflate":
                body = zlib.decompress(body, -15)
            elif codec != "null":
                raise ValueError(f"{path}: unsupported codec {codec!r}")
            bpos = 0
            for _ in range(count):
                v, bpos = _avro_decode(schema, body, bpos)
                rows.append(v)
        yield _rows_to_table(rows)

    return [lambda p=p: one(p) for p in files]


def write_avro(rows: list[dict], schema: dict, path: str) -> None:
    """Write one Avro container file (test/round-trip support)."""
    import json as _json
    import os as _os

    sync = _os.urandom(16)
    body = b"".join(_avro_encode(schema, r) for r in rows)
    meta = {"avro.schema": _json.dumps(schema).encode(),
            "avro.codec": b"null"}
    with open(path, "wb") as f:
        f.write(_AVRO_MAGIC)
        f.write(_zz_write(len(meta)))
        for k, v in meta.items():
            kk = k.encode()
            f.write(_zz_write(len(kk)) + kk)
            f.write(_zz_write(len(v)) + v)
        f.write(_zz_write(0))
        f.write(sync)
        f.write(_zz_write(len(rows)))
        f.write(_zz_write(len(body)))
        f.write(body)
        f.write(sync)


# ------------------------------------------------------------ webdataset
def webdataset_tasks(paths, parallelism: int) -> list[ReadTask]:
    """WebDataset tar shards → one row per sample (ray:
    data/_internal/datasource/webdataset_datasource.py).  Files sharing
    a basename form one sample; each extension becomes a bytes column
    ("__key__" carries the basename)."""
    import tarfile

    files = _expand_paths(paths, ".tar")

    def one(path: str) -> Iterator[Block]:
        samples: dict[str, dict] = {}
        order: list[str] = []
        with tarfile.open(path) as tf:
            for m in tf:
                if not m.isfile():
                    continue
                base, _, ext = m.name.partition(".")
                if base not in samples:
                    samples[base] = {"__key__": base}
                    order.append(base)
                samples[base][ext] = tf.extractfile(m).read()
        yield _rows_to_table([samples[k] for k in order])

    return [lambda p=p: one(p) for p in files]


# ----------------------------------------------------------- huggingface
def huggingface_tasks(dataset, parallelism: int = 8) -> list[ReadTask]:
    """An in-memory/local `datasets.Dataset` → blocks via its arrow data
    (ray: data/_internal/datasource/huggingface_datasource.py; works
    fully offline on locally built/saved datasets — this box has no
    egress for hub downloads)."""
    table = dataset.data.table if hasattr(dataset.data, "table") \
        else dataset.data
    table = table.combine_chunks()
    n = max(1, min(parallelism, table.num_rows or 1))
    chunk = (table.num_rows + n - 1) // n
    slices = [table.slice(i, chunk)
              for i in range(0, table.num_rows, chunk)] or [table]

    def mk(t):
        def read() -> Iterator[Block]:
            yield t

        return read

    return [mk(t) for t in slices]
