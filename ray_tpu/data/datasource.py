"""Datasources: read tasks that produce blocks, write helpers.

Analog of ray: python/ray/data/datasource/ (parquet/csv/json/... over
pyarrow.fs).  A ReadTask is a zero-arg callable returning an iterator of
blocks; the planner turns each into one ray_tpu task so reads parallelize
and stream like any other operator.
"""
from __future__ import annotations

import glob as globmod
import os
from typing import Any, Callable, Iterable, Iterator

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, _rows_to_table, _to_table

ReadTask = Callable[[], Iterator[Block]]


def _expand_paths(paths: str | list[str], suffix: str | None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(globmod.glob(pat)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


# ------------------------------------------------------------------ reads
def range_tasks(n: int, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    sizes = [n // parallelism + (1 if i < n % parallelism else 0)
             for i in range(parallelism)]
    tasks, start = [], 0
    for sz in sizes:
        s, e = start, start + sz

        def read(s=s, e=e) -> Iterator[Block]:
            yield pa.table({"id": np.arange(s, e, dtype=np.int64)})

        tasks.append(read)
        start = e
    return tasks


def items_tasks(items: list, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + parallelism - 1) // parallelism
    tasks = []
    for i in range(0, len(items), chunk):
        part = items[i:i + chunk]

        def read(part=part) -> Iterator[Block]:
            yield _rows_to_table(part)

        tasks.append(read)
    return tasks


def parquet_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".parquet")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        yield pq.read_table(path)

    return [lambda p=p: one(p) for p in files]


def csv_tasks(paths, parallelism: int, **opts) -> list[ReadTask]:
    files = _expand_paths(paths, ".csv")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.csv as pcsv

        yield pcsv.read_csv(path)

    return [lambda p=p: one(p) for p in files]


def json_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".json")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.json as pjson

        yield pjson.read_json(path)

    return [lambda p=p: one(p) for p in files]


def text_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": lines})

    return [lambda p=p: one(p) for p in files]


def numpy_tasks(arrays: list[np.ndarray], column: str = "data",
                ) -> list[ReadTask]:
    tasks = []
    for arr in arrays:
        def read(arr=arr) -> Iterator[Block]:
            yield _to_table({column: arr})

        tasks.append(read)
    return tasks


def generator_tasks(fns: list[Callable[[], Iterable[Any]]]) -> list[ReadTask]:
    """Custom per-shard generators (streaming token pipelines)."""
    def wrap(fn):
        def read() -> Iterator[Block]:
            for chunk in fn():
                yield _to_table(chunk) if not isinstance(chunk, pa.Table) \
                    else chunk

        return read

    return [wrap(fn) for fn in fns]


# ----------------------------------------------------------------- writes
def write_block(block: Block, path: str, fmt: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, out)
    elif fmt == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(block, out)
    elif fmt == "json":
        block.to_pandas().to_json(out, orient="records", lines=True)
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return out
