"""Datasources: read tasks that produce blocks, write helpers.

Analog of ray: python/ray/data/datasource/ (parquet/csv/json/... over
pyarrow.fs).  A ReadTask is a zero-arg callable returning an iterator of
blocks; the planner turns each into one ray_tpu task so reads parallelize
and stream like any other operator.
"""
from __future__ import annotations

import glob as globmod
import os
from typing import Any, Callable, Iterable, Iterator

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import Block, _rows_to_table, _to_table

ReadTask = Callable[[], Iterator[Block]]


def _expand_paths(paths: str | list[str], suffix: str | None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, f"*{suffix}" if suffix else "*")
            out.extend(sorted(globmod.glob(pat)))
        elif any(c in p for c in "*?["):
            out.extend(sorted(globmod.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files match {paths}")
    return out


# ------------------------------------------------------------------ reads
def range_tasks(n: int, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, n or 1))
    sizes = [n // parallelism + (1 if i < n % parallelism else 0)
             for i in range(parallelism)]
    tasks, start = [], 0
    for sz in sizes:
        s, e = start, start + sz

        def read(s=s, e=e) -> Iterator[Block]:
            yield pa.table({"id": np.arange(s, e, dtype=np.int64)})

        tasks.append(read)
        start = e
    return tasks


def items_tasks(items: list, parallelism: int) -> list[ReadTask]:
    parallelism = max(1, min(parallelism, len(items) or 1))
    chunk = (len(items) + parallelism - 1) // parallelism
    tasks = []
    for i in range(0, len(items), chunk):
        part = items[i:i + chunk]

        def read(part=part) -> Iterator[Block]:
            yield _rows_to_table(part)

        tasks.append(read)
    return tasks


def parquet_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".parquet")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.parquet as pq

        yield pq.read_table(path)

    return [lambda p=p: one(p) for p in files]


def csv_tasks(paths, parallelism: int, **opts) -> list[ReadTask]:
    files = _expand_paths(paths, ".csv")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.csv as pcsv

        yield pcsv.read_csv(path)

    return [lambda p=p: one(p) for p in files]


def json_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, ".json")

    def one(path: str) -> Iterator[Block]:
        import pyarrow.json as pjson

        yield pjson.read_json(path)

    return [lambda p=p: one(p) for p in files]


def text_tasks(paths, parallelism: int) -> list[ReadTask]:
    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        with open(path) as f:
            lines = [ln.rstrip("\n") for ln in f]
        yield pa.table({"text": lines})

    return [lambda p=p: one(p) for p in files]


def numpy_tasks(arrays: list[np.ndarray], column: str = "data",
                ) -> list[ReadTask]:
    tasks = []
    for arr in arrays:
        def read(arr=arr) -> Iterator[Block]:
            yield _to_table({column: arr})

        tasks.append(read)
    return tasks


def generator_tasks(fns: list[Callable[[], Iterable[Any]]]) -> list[ReadTask]:
    """Custom per-shard generators (streaming token pipelines)."""
    def wrap(fn):
        def read() -> Iterator[Block]:
            for chunk in fn():
                yield _to_table(chunk) if not isinstance(chunk, pa.Table) \
                    else chunk

        return read

    return [wrap(fn) for fn in fns]


def image_tasks(paths, parallelism: int, size: tuple | None = None,
                mode: str | None = None) -> list[ReadTask]:
    """Image files → {"image": [h, w, c] uint8 ndarray, "path": str}
    rows (ray: data/datasource/image_datasource.py; PIL decode)."""
    files = _expand_paths(paths, None)
    files = [f for f in files if f.lower().endswith(
        (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".tiff", ".webp"))] \
        or files

    def one(path: str) -> Iterator[Block]:
        from PIL import Image

        img = Image.open(path)
        if mode:
            img = img.convert(mode)
        if size:
            img = img.resize(size)
        arr = np.asarray(img)
        # Arrow blocks carry tensors as flattened fixed-size lists; the
        # original shape rides alongside so consumers reshape exactly
        # (np.asarray(row["image"], np.uint8).reshape(row["shape"])).
        yield _to_table({"image": arr[None],
                         "shape": [list(arr.shape)],
                         "path": [path]})

    return [lambda p=p: one(p) for p in files]


def binary_tasks(paths, parallelism: int) -> list[ReadTask]:
    """Whole files as bytes → {"bytes", "path"} rows (ray:
    data/datasource/binary_datasource.py)."""
    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        with open(path, "rb") as f:
            data = f.read()
        yield pa.table({"bytes": [data], "path": [path]})

    return [lambda p=p: one(p) for p in files]


# TFRecord framing: u64le length, u32le masked-crc32c(length), payload,
# u32le masked-crc32c(payload).  crc32c implemented here (Castagnoli
# polynomial, table-driven) — no tensorflow/crc32c wheel in the env.
_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def tfrecord_tasks(paths, parallelism: int,
                   verify: bool = False) -> list[ReadTask]:
    """TFRecord files → one {"record": bytes} row per record (ray:
    data/datasource/tfrecords_datasource.py; raw records — Example proto
    parsing is the caller's map step, keeping TF out of the core).

    Length-header CRCs are always checked (8 bytes each — cheap, and
    they catch framing corruption).  verify=True also checks payload
    CRCs; that runs the pure-Python crc32c over every byte, so it is
    off by default (the reference skips payload verification too)."""
    import struct as _struct

    files = _expand_paths(paths, None)

    def one(path: str) -> Iterator[Block]:
        records = []
        with open(path, "rb") as f:
            while True:
                head = f.read(8)
                if len(head) < 8:
                    break
                (length,) = _struct.unpack("<Q", head)
                (len_crc,) = _struct.unpack("<I", f.read(4))
                if len_crc != _masked_crc(head):
                    raise ValueError(f"{path}: corrupt length crc")
                payload = f.read(length)
                (data_crc,) = _struct.unpack("<I", f.read(4))
                if verify and data_crc != _masked_crc(payload):
                    raise ValueError(f"{path}: corrupt record crc")
                records.append(payload)
        yield pa.table({"record": records})

    return [lambda p=p: one(p) for p in files]


def _write_tfrecord(block: Block, out: str) -> None:
    import struct as _struct

    acc_cols = block.column_names
    col = "record" if "record" in acc_cols else acc_cols[0]
    with open(out, "wb") as f:
        for v in block.column(col).to_pylist():
            payload = v if isinstance(v, bytes) else str(v).encode()
            head = _struct.pack("<Q", len(payload))
            f.write(head)
            f.write(_struct.pack("<I", _masked_crc(head)))
            f.write(payload)
            f.write(_struct.pack("<I", _masked_crc(payload)))


# ----------------------------------------------------------------- writes
def write_block(block: Block, path: str, fmt: str, index: int) -> str:
    os.makedirs(path, exist_ok=True)
    out = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, out)
    elif fmt == "csv":
        import pyarrow.csv as pcsv

        pcsv.write_csv(block, out)
    elif fmt == "json":
        block.to_pandas().to_json(out, orient="records", lines=True)
    elif fmt == "tfrecord":
        _write_tfrecord(block, out)
    else:
        raise ValueError(f"unknown write format {fmt!r}")
    return out
