"""Preprocessor base: fit statistics over a Dataset, transform anywhere.

Analog of ray: python/ray/data/preprocessor.py (Preprocessor.fit :88,
transform :137, transform_batch :161; subclasses implement _fit and a
per-batch transform).  Design difference: the reference fits through
Dataset.aggregate (its own Arrow aggregate layer); here fitting is a
map_batches over blocks emitting pickled per-block partials that the
driver folds — the same two-phase tree the executor already parallelizes,
with no extra aggregate machinery.  Batches are numpy dicts end-to-end
(the device-feed format of iter_jax/torch_batches).
"""
from __future__ import annotations

import pickle
from typing import Any, Callable

import numpy as np


class PreprocessorNotFittedException(RuntimeError):
    """transform() called before fit() on a stateful preprocessor."""


class Preprocessor:
    """Fit once against a Dataset, then transform Datasets or batches.

    Subclasses override `_fit(ds)` (compute and store `self.stats_`;
    stateless preprocessors leave the default no-op) and
    `_transform_batch(batch: dict[str, np.ndarray]) -> dict`.
    """

    _is_fittable = True

    # ------------------------------------------------------------ public
    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform(self, ds):
        self._check_fitted()
        return ds.map_batches(self._transform_batch, batch_format="numpy")

    def transform_batch(self, batch: dict) -> dict:
        self._check_fitted()
        return self._transform_batch(
            {k: np.asarray(v) for k, v in batch.items()})

    # --------------------------------------------------------- overrides
    def _fit(self, ds) -> None:  # noqa: B027 - optional hook
        pass

    def _transform_batch(self, batch: dict) -> dict:
        raise NotImplementedError

    # ----------------------------------------------------------- helpers
    def _check_fitted(self) -> None:
        if self._is_fittable and not getattr(self, "_fitted", False):
            raise PreprocessorNotFittedException(
                f"{type(self).__name__} must be fit before transform; "
                "call .fit(ds) or .fit_transform(ds)")

    def __repr__(self):
        state = "" if not self._is_fittable else (
            " (fitted)" if getattr(self, "_fitted", False)
            else " (not fitted)")
        return f"{type(self).__name__}{state}"


def aggregate_blocks(ds, partial_fn: Callable[[dict], Any],
                     combine_fn: Callable[[Any, Any], Any]) -> Any:
    """Two-phase fit: map each block to a partial statistic (runs as
    distributed tasks), fold the partials on the driver.

    Partials cross the object store pickled inside a binary column, so a
    partial can be any picklable value (dicts of Counters, numpy
    moments, ...) without needing an Arrow representation.
    """

    def per_block(batch: dict) -> dict:
        return {"partial": np.array([pickle.dumps(partial_fn(batch))],
                                    dtype=object)}

    rows = ds.map_batches(per_block, batch_format="numpy").take_all()
    partials = [pickle.loads(r["partial"]) for r in rows]
    if not partials:
        raise ValueError("cannot fit a preprocessor on an empty dataset")
    acc = partials[0]
    for p in partials[1:]:
        acc = combine_fn(acc, p)
    return acc
