"""DataIterator: batch iteration with prefetch and device placement.

Analog of ray: python/ray/data/iterator.py:60 (DataIterator.iter_batches)
+ train integration (streaming_split shards feeding per-host device
prefetch).  TPU-native addition: `iter_jax_batches` double-buffers
jax.device_put so host→HBM transfer of batch N+1 overlaps step N
(SURVEY §7 step 6).
"""
from __future__ import annotations

import collections
import queue
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import BlockAccessor


def _rebatch(blocks: Iterable, batch_size: int | None, batch_format: str,
             drop_last: bool) -> Iterator[Any]:
    """Slice a stream of blocks into exact-size batches."""
    if batch_size is None:
        for b in blocks:
            acc = BlockAccessor.for_block(b)
            if acc.num_rows():
                yield acc.to_batch(batch_format)
        return
    buf: list = []
    buffered = 0
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        if acc.num_rows() == 0:
            continue
        buf.append(acc.block)
        buffered += acc.num_rows()
        while buffered >= batch_size:
            merged = BlockAccessor.concat(buf)
            head = merged.slice(0, batch_size)
            rest = merged.slice(batch_size, merged.num_rows - batch_size)
            yield BlockAccessor(head).to_batch(batch_format)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
    if buffered and not drop_last:
        merged = BlockAccessor.concat(buf)
        yield BlockAccessor(merged).to_batch(batch_format)


def _shuffle_buffered(batches: Iterator, buffer_size: int, seed) -> Iterator:
    rng = np.random.default_rng(seed)
    pool: list = []
    for b in batches:
        pool.append(b)
        if len(pool) >= buffer_size:
            idx = rng.integers(len(pool))
            pool[idx], pool[-1] = pool[-1], pool[idx]
            yield pool.pop()
    rng.shuffle(pool)
    yield from pool


class DataIterator:
    """Iterates batches from a block-ref stream (possibly still executing)."""

    def __init__(self, ref_iter_factory: Callable[[], Iterator]):
        self._factory = ref_iter_factory

    def _block_stream(self, prefetch: int) -> Iterator:
        """Fetch blocks with a lookahead of `prefetch` in-flight gets."""
        refs = self._factory()
        window: collections.deque = collections.deque()
        for ref in refs:
            window.append(ref)
            if len(window) > prefetch:
                yield ray_tpu.get(window.popleft())
        while window:
            yield ray_tpu.get(window.popleft())

    def iter_batches(self, *, batch_size: int | None = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 2,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: int | None = None,
                     local_shuffle_seed: int | None = None) -> Iterator[Any]:
        batches = _rebatch(self._block_stream(max(1, prefetch_batches)),
                           batch_size, batch_format, drop_last)
        if local_shuffle_buffer_size:
            batches = _shuffle_buffered(batches, local_shuffle_buffer_size,
                                        local_shuffle_seed)
        # Background-thread prefetch decouples fetch/convert from consumer.
        q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_batches))
        DONE, err_box = object(), []

        def pump():
            try:
                for b in batches:
                    q.put(b)
            except BaseException as e:  # noqa: BLE001
                err_box.append(e)
            finally:
                q.put(DONE)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                if err_box:
                    raise err_box[0]
                return
            yield item

    def iter_rows(self) -> Iterator[dict]:
        for batch in self.iter_batches(batch_size=None,
                                       batch_format="pyarrow"):
            yield from BlockAccessor.for_block(batch).iter_rows()

    # ------------------------------------------------------------- device
    def iter_jax_batches(self, *, batch_size: int, sharding=None,
                         dtypes: dict | None = None,
                         drop_last: bool = True,
                         prefetch_batches: int = 2) -> Iterator[Any]:
        """Numpy batches → jax arrays on device, double-buffered: device_put
        of the next batch is issued before the current one is yielded, so
        host→HBM DMA overlaps the consumer's step."""
        import jax

        def to_device(np_batch: dict):
            out = {}
            for k, v in np_batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, sharding)
            return out

        it = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                               drop_last=drop_last,
                               prefetch_batches=prefetch_batches)
        prev = None
        for np_batch in it:
            cur = to_device(np_batch)     # async dispatch; no host sync
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes: dict | None = None,
                           device: str | None = None,
                           drop_last: bool = False,
                           prefetch_batches: int = 2,
                           local_shuffle_buffer_size: int | None = None,
                           ) -> Iterator[dict]:
        """Numpy batches → torch tensors (ray: iterator.iter_torch_batches)
        — the host-side torch feed for TorchTrainer loops."""
        import torch

        for np_batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                drop_last=drop_last, prefetch_batches=prefetch_batches,
                local_shuffle_buffer_size=local_shuffle_buffer_size):
            out = {}
            for k, v in np_batch.items():
                t = torch.as_tensor(v)
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out

    def materialize_numpy(self, limit: int | None = None) -> dict:
        """Gather everything into one numpy dict (tests/small data)."""
        blocks = [BlockAccessor.for_block(b).block
                  for b in self._block_stream(4)]
        merged = BlockAccessor.concat(blocks) if blocks else None
        if merged is None:
            return {}
        if limit is not None:
            merged = merged.slice(0, limit)
        return BlockAccessor(merged).to_numpy()
