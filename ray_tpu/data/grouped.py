"""GroupedData: aggregations after groupby (ray: python/ray/data/grouped_data.py).

Two-stage: per-block partial aggregation in tasks (mean decomposes into
sum+count), single combine task — the standard map-side pre-aggregation
shuffle.
"""
from __future__ import annotations

from ray_tpu.data import logical as L


class GroupedData:
    def __init__(self, dataset, keys: list[str]):
        self._ds = dataset
        self._keys = keys

    def _agg(self, pairs: list[tuple[str, str]]):
        from ray_tpu.data.dataset import Dataset

        return Dataset(self._ds._plan.with_op(
            L.Aggregate(self._keys, pairs)))

    def count(self):
        # count needs a column; use the first key or synthesize
        col = self._keys[0] if self._keys else None
        if col is None:
            raise ValueError("global count(): use Dataset.count()")
        return self._agg([("count", col)])

    def sum(self, col: str):
        return self._agg([("sum", col)])

    def min(self, col: str):
        return self._agg([("min", col)])

    def max(self, col: str):
        return self._agg([("max", col)])

    def mean(self, col: str):
        return self._agg([("mean", col)])

    def aggregate(self, **aggs: str):
        """aggregate(total="sum:value", avg="mean:value")"""
        pairs = []
        for _name, spec in aggs.items():
            op, col = spec.split(":")
            pairs.append((op, col))
        return self._agg(pairs)
