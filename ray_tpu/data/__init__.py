"""ray_tpu.data — streaming distributed data library
(reference: python/ray/data; SURVEY §2.3 Ray Data, §3.6 execution).

Lazy logical plans over arrow blocks, executed by a pull-based streaming
executor on ray_tpu tasks/actors; device-ready sharded batches via
iter_jax_batches / streaming_split.
"""
from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import (Dataset, from_arrow, from_generators,  # noqa: F401,E501
                                  from_huggingface, from_items,
                                  from_numpy, from_pandas, range,
                                  read_avro, read_binary_files, read_csv,
                                  read_images, read_json, read_parquet,
                                  read_sql, read_text, read_tfrecords,
                                  read_webdataset)
from ray_tpu.data.iterator import DataIterator  # noqa: F401
from ray_tpu.data.preprocessor import (Preprocessor,  # noqa: F401
                                       PreprocessorNotFittedException)
from ray_tpu.data import preprocessors  # noqa: F401
