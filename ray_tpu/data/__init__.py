"""ray_tpu.data — streaming distributed data library
(reference: python/ray/data; SURVEY §2.3 Ray Data, §3.6 execution).

Lazy logical plans over arrow blocks, executed by a pull-based streaming
executor on ray_tpu tasks/actors; device-ready sharded batches via
iter_jax_batches / streaming_split.
"""
from ray_tpu.data.block import Block, BlockAccessor  # noqa: F401
from ray_tpu.data.context import DataContext  # noqa: F401
from ray_tpu.data.dataset import (Dataset, from_arrow, from_arrow_refs,  # noqa: F401,E501
                                  from_generators, from_huggingface,
                                  from_items, from_numpy,
                                  from_numpy_refs, from_pandas,
                                  from_pandas_refs, range, range_tensor,
                                  read_avro, read_binary_files, read_csv,
                                  read_datasource, read_images,
                                  read_json, read_numpy, read_parquet,
                                  read_parquet_bulk, read_sql, read_text,
                                  read_tfrecords, read_webdataset,
                                  set_progress_bars)
from ray_tpu.data.datasource import ReadTask  # noqa: F401
from ray_tpu.data.interfaces import (ActorPoolStrategy, Datasink,  # noqa: F401,E501
                                     Datasource, ExecutionOptions,
                                     ExecutionResources)
from ray_tpu.data.iterator import DataIterator  # noqa: F401

# Block schemas ARE pyarrow schemas here (ray wraps them in its own
# Schema type; the accessor surface .names/.types matches).
import pyarrow as _pa  # noqa: E402

Schema = _pa.Schema
from ray_tpu.data.preprocessor import (Preprocessor,  # noqa: F401
                                       PreprocessorNotFittedException)
from ray_tpu.data import preprocessors  # noqa: F401
