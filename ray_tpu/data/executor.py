"""Pull-based streaming executor over ray_tpu tasks/actors.

Analog of ray: python/ray/data/_internal/execution/streaming_executor.py:48
(scheduling step :272, select_operator_to_run streaming_executor_state.py:517)
and the physical operators in _internal/execution/operators/.

Design: physical operators form a chain; the driver loop each tick
  1. harvests finished task refs from every operator (ray_tpu.wait, t=0),
  2. moves outputs downstream,
  3. grants new task launches to the most downstream operator that has
     input + budget (pull-based: draining late operators first keeps the
     pipeline's memory footprint bounded — the backpressure analog of the
     reference's resource-budget select_operator_to_run),
  4. yields final output block refs as they complete (streaming: consumers
     iterate while upstream reads are still running).

Blocks cross operator boundaries as ObjectRefs; block payloads live in the
shm object store, not the driver heap.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Iterator

import ray_tpu
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data import logical as L

DEFAULT_MAX_TASKS = 8


# ---------------------------------------------------------------- UDF glue
def _make_block_fn(op: L.LogicalOp) -> Callable:
    """Turn a logical transform into block(s)->blocks callable run inside a
    worker task."""
    if isinstance(op, L.FlatMap):
        fn = op.fn
        is_flat = op.name.startswith(("FlatMap", "Fused"))

        def run(block):
            from ray_tpu.data.block import _rows_to_table

            rows_out = []
            for row in BlockAccessor.for_block(block).iter_rows():
                rows_out.extend(fn(row))
            if not rows_out:
                return BlockAccessor.empty()
            return _rows_to_table(rows_out)

        return run
    if isinstance(op, L.MapRows):
        fn = op.fn

        def run(block):
            from ray_tpu.data.block import _rows_to_table

            rows = [fn(r) for r in
                    BlockAccessor.for_block(block).iter_rows()]
            return _rows_to_table(rows) if rows else BlockAccessor.empty()

        return run
    if isinstance(op, L.Filter):
        fn = op.fn

        def run(block):
            from ray_tpu.data.block import _rows_to_table

            rows = [r for r in
                    BlockAccessor.for_block(block).iter_rows() if fn(r)]
            return _rows_to_table(rows) if rows else block.slice(0, 0)

        return run
    if isinstance(op, L.MapBatches):
        fn = op.fn
        fmt = op.batch_format
        bs = op.batch_size

        def run(block, fn=fn):
            from ray_tpu.data.block import _to_table

            acc = BlockAccessor.for_block(block)
            n = acc.num_rows()
            step = bs or n or 1
            outs = []
            for s in range(0, n, step):
                batch = BlockAccessor(acc.slice(s, min(s + step, n))) \
                    .to_batch(fmt)
                res = fn(batch)
                outs.append(_to_table(res))
            if not outs:
                return BlockAccessor.empty()
            return BlockAccessor.concat(outs)

        return run
    raise TypeError(f"not a map-like op: {op}")


@ray_tpu.remote
def _run_block_task(fn, block):
    return fn(block)


@ray_tpu.remote
def _read_task(read_fn):
    return BlockAccessor.concat(list(read_fn()))


class _BatchActor:
    """Stateful UDF host for compute="actors" (ray: ActorPoolMapOperator)."""

    def __init__(self, cls, ctor_args, fn_args, fn_kwargs, batch_format,
                 batch_size):
        self.udf = cls(*ctor_args)
        self.fn_args = fn_args
        self.fn_kwargs = fn_kwargs
        self.batch_format = batch_format
        self.batch_size = batch_size

    def run(self, block):
        from ray_tpu.data.block import _to_table

        acc = BlockAccessor.for_block(block)
        n = acc.num_rows()
        step = self.batch_size or n or 1
        outs = []
        for s in range(0, n, step):
            batch = BlockAccessor(acc.slice(s, min(s + step, n))) \
                .to_batch(self.batch_format)
            outs.append(_to_table(self.udf(
                batch, *self.fn_args, **self.fn_kwargs)))
        return BlockAccessor.concat(outs) if outs else BlockAccessor.empty()


# ------------------------------------------------------------- operators
class PhysicalOp:
    name = "op"

    def __init__(self):
        self.inq: collections.deque = collections.deque()
        self.in_done = False
        self.outq: collections.deque = collections.deque()
        self.inflight: dict[Any, Any] = {}
        # Execution stats (ray: data/_internal/stats.py per-op metrics).
        self.stat_launched = 0
        self.stat_blocks_out = 0
        self.stat_started: float | None = None
        self.stat_finished: float | None = None
        # Launch-order emission: blocks leave each operator in the order
        # they entered it, so downstream sees dataset order (ray data's
        # default preserve_order streaming semantics; take(5) = first rows).
        self.order: collections.deque = collections.deque()
        self._completed: set = set()
        self.done = False

    def add_input(self, ref) -> None:
        self.inq.append(ref)

    def mark_input_done(self) -> None:
        self.in_done = True

    def can_launch(self) -> bool:
        return bool(self.inq) and len(self.inflight) < self.max_tasks

    def launch_one(self) -> None:
        raise NotImplementedError

    def _track(self, ref, token) -> None:
        self.inflight[ref] = token
        self.order.append(ref)

    def _drain_in_order(self) -> None:
        while self.order and self.order[0] in self._completed:
            ref = self.order.popleft()
            self._completed.discard(ref)
            self.outq.append(ref)

    def harvest(self) -> None:
        if not self.inflight:
            self._drain_in_order()
            self._maybe_finish()
            return
        done, _ = ray_tpu.wait(list(self.inflight), num_returns=len(
            self.inflight), timeout=0)
        for ref in done:
            self.inflight.pop(ref)
            self._completed.add(ref)
        self._drain_in_order()
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        if self.in_done and not self.inq and not self.inflight:
            self.done = True

    max_tasks = DEFAULT_MAX_TASKS


class InputOp(PhysicalOp):
    """Read stage: one task per ReadTask."""

    name = "Input"

    def __init__(self, read_tasks, max_tasks=DEFAULT_MAX_TASKS):
        super().__init__()
        for t in read_tasks:
            self.inq.append(t)
        self.in_done = True
        self.max_tasks = max_tasks

    def launch_one(self) -> None:
        t = self.inq.popleft()
        self._track(_read_task.remote(t), t)


class TaskMapOp(PhysicalOp):
    name = "Map(tasks)"

    def __init__(self, op: L.LogicalOp, max_tasks=DEFAULT_MAX_TASKS):
        super().__init__()
        self.fn = _make_block_fn(op)
        self.name = f"Map[{op.name}]"
        self.max_tasks = max_tasks
        self.remote = _run_block_task
        if isinstance(op, L.MapBatches) and (op.num_cpus or op.num_tpus):
            opts = {}
            if op.num_cpus:
                opts["num_cpus"] = op.num_cpus
            if op.num_tpus:
                opts["num_tpus"] = op.num_tpus
            self.remote = _run_block_task.options(**opts)

    def launch_one(self) -> None:
        ref = self.inq.popleft()
        self._track(self.remote.remote(self.fn, ref), ref)


class ActorMapOp(PhysicalOp):
    """compute="actors": fixed pool, blocks go to idle actors."""

    name = "Map(actors)"

    def __init__(self, op: L.MapBatches):
        super().__init__()
        conc = op.concurrency or 2
        if isinstance(conc, tuple):
            conc = conc[1]
        self.pool_size = int(conc)
        self.max_tasks = self.pool_size
        self.name = f"ActorMap[{getattr(op.fn, '__name__', 'udf')}]"
        opts = {}
        if op.num_cpus:
            opts["num_cpus"] = op.num_cpus
        if op.num_tpus:
            opts["num_tpus"] = op.num_tpus
        cls = ray_tpu.remote(_BatchActor)
        if opts:
            cls = cls.options(**opts)
        self.actors = [
            cls.remote(op.fn, op.fn_constructor_args, op.fn_args,
                       op.fn_kwargs, op.batch_format, op.batch_size)
            for _ in range(self.pool_size)
        ]
        self.idle = list(self.actors)
        self.ref_actor: dict[Any, Any] = {}

    def can_launch(self) -> bool:
        return bool(self.inq) and bool(self.idle)

    def launch_one(self) -> None:
        block_ref = self.inq.popleft()
        actor = self.idle.pop()
        ref = actor.run.remote(block_ref)
        self._track(ref, block_ref)
        self.ref_actor[ref] = actor

    def harvest(self) -> None:
        if self.inflight:
            done, _ = ray_tpu.wait(list(self.inflight),
                                   num_returns=len(self.inflight), timeout=0)
            for ref in done:
                self.inflight.pop(ref)
                self.idle.append(self.ref_actor.pop(ref))
                self._completed.add(ref)
            self._drain_in_order()
        self._maybe_finish()
        if self.done:
            for a in self.actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass
            self.actors = []


class AllToAllOp(PhysicalOp):
    """Barrier ops: repartition / shuffle / sort / aggregate.  Gathers all
    input refs, then runs a fan-out+reduce on the driver via tasks."""

    name = "AllToAll"

    def __init__(self, op: L.LogicalOp):
        super().__init__()
        self.op = op
        self.name = f"AllToAll[{op.name}]"
        self._launched = False
        self._reduce_refs: list = []

    def can_launch(self) -> bool:
        return self.in_done and not self._launched and not self.inflight

    def launch_one(self) -> None:
        self._launched = True
        refs = list(self.inq)
        self.inq.clear()
        for ref in _all_to_all(self.op, refs):
            self._track(ref, ref)

    def _maybe_finish(self) -> None:
        if self._launched and not self.inflight:
            self.done = True


class LimitOp(PhysicalOp):
    """Early-stopping limit: truncates and stops consuming past n rows."""

    name = "Limit"

    def __init__(self, n: int):
        super().__init__()
        self.n = n
        self.taken = 0

    def can_launch(self) -> bool:
        return bool(self.inq)

    def launch_one(self) -> None:
        ref = self.inq.popleft()
        if self.taken >= self.n:
            return
        block = ray_tpu.get(ref)
        rows = BlockAccessor.for_block(block).num_rows()
        if self.taken + rows <= self.n:
            self.outq.append(ref)
            self.taken += rows
        else:
            keep = self.n - self.taken
            self.outq.append(ray_tpu.put(block.slice(0, keep)))
            self.taken = self.n
        if self.taken >= self.n:
            self.in_done = True
            self.inq.clear()

    def harvest(self) -> None:
        self._maybe_finish()


# ------------------------------------------------------- all-to-all tasks
@ray_tpu.remote
def _split_block(block, n: int, key, shuffle_seed):
    """Map side of the shuffle: partition one block n ways."""
    import numpy as np

    acc = BlockAccessor.for_block(block)
    rows = acc.num_rows()
    if rows == 0:
        return [block] * n
    if key is not None:                       # range-ish partition by hash
        cols = acc.to_numpy()
        h = np.array([hash(x) % n for x in cols[key]])
        return [block.take(np.nonzero(h == i)[0]) for i in range(n)]
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        perm = rng.permutation(rows)
        parts = np.array_split(perm, n)
        return [block.take(p) for p in parts]
    parts = np.array_split(np.arange(rows), n)
    return [block.take(p) for p in parts]


@ray_tpu.remote
def _concat_blocks(*parts):
    return BlockAccessor.concat(list(parts))


@ray_tpu.remote
def _sample_block(block, key, k):
    """Sample up to k key values from one block (ray: SortTaskSpec
    sample_boundaries, sort_task_spec.py:91)."""
    import numpy as np

    acc = BlockAccessor.for_block(block)
    n = acc.num_rows()
    if n == 0:
        return np.array([])
    col = np.asarray(acc.to_numpy()[key])
    if n <= k:
        return col
    idx = np.linspace(0, n - 1, k).astype(np.int64)
    return col[idx]


@ray_tpu.remote
def _range_partition(block, key, desc, boundaries):
    """Map side of the distributed sort: sort one block, then cut it at
    the sampled boundaries into len(boundaries)+1 runs (ray:
    sort_task_spec.py:149 map phase)."""
    import numpy as np

    srt = block.sort_by([(key, "descending" if desc else "ascending")])
    n_parts = len(boundaries) + 1
    acc = BlockAccessor.for_block(srt)
    rows = acc.num_rows()
    if rows == 0:
        return [srt] * n_parts
    col = np.asarray(acc.to_numpy()[key])
    if desc:
        # col is descending; boundaries ascending.  Partition j holds the
        # j-th range from the TOP; works for any sortable dtype (no
        # negation trick, so strings partition too).
        asc = col[::-1]
        cuts = [rows - int(np.searchsorted(asc, b, side="right"))
                for b in boundaries[::-1]]
    else:
        cuts = [int(np.searchsorted(col, b, side="left"))
                for b in boundaries]
    out, prev = [], 0
    for c in list(cuts) + [rows]:
        c = int(c)
        out.append(srt.slice(prev, c - prev))
        prev = c
    return out


@ray_tpu.remote
def _merge_sorted(key, desc, *blocks):
    merged = BlockAccessor.concat(list(blocks))
    return merged.sort_by([(key, "descending" if desc else "ascending")])


@ray_tpu.remote
def _hash_partition_rows(block, keys, n):
    """Partition one block n ways by a deterministic hash of the key
    columns (process-independent, unlike builtin hash)."""
    import numpy as np
    import pandas as pd

    acc = BlockAccessor.for_block(block)
    if acc.num_rows() == 0:
        return [block] * n
    df = acc.to_pandas()
    h = pd.util.hash_pandas_object(df[keys].astype(str).agg("\0".join,
                                                            axis=1),
                                   index=False).to_numpy()
    part = (h % n).astype(np.int64)
    return [block.take(np.nonzero(part == i)[0]) for i in range(n)]


@ray_tpu.remote
def _partial_agg(block, keys, aggs):
    df = BlockAccessor.for_block(block).to_pandas()
    if df.empty:
        return block.slice(0, 0)
    import pandas as pd  # noqa: F401

    partial = {}
    g = df.groupby(keys) if keys else None
    cols = {}
    for agg_name, col in aggs:
        series = (g[col] if g is not None else df[col])
        if agg_name == "mean":      # decompose for correct combine
            cols[f"sum({col})"] = series.sum()
            cols[f"count({col})"] = series.count()
        elif agg_name == "count":
            cols["count()"] = series.count()
        else:
            cols[f"{agg_name}({col})"] = getattr(series, agg_name)()
    import pandas as pd

    if g is not None:
        out = pd.DataFrame(cols).reset_index()
    else:
        out = pd.DataFrame({k: [v] for k, v in cols.items()})
    import pyarrow as pa

    return pa.Table.from_pandas(out, preserve_index=False)


@ray_tpu.remote
def _final_agg(keys, aggs, *partials):
    import pandas as pd
    import pyarrow as pa

    df = BlockAccessor.concat(list(partials)).to_pandas()
    if df.empty:
        return pa.table({})
    combine = {}
    rename = {}
    for agg_name, col in aggs:
        if agg_name == "mean":
            combine[f"sum({col})"] = "sum"
            combine[f"count({col})"] = "sum"
        elif agg_name == "count":
            combine["count()"] = "sum"
        elif agg_name in ("sum", "min", "max"):
            combine[f"{agg_name}({col})"] = agg_name
        else:
            combine[f"{agg_name}({col})"] = agg_name
        rename[f"{agg_name}({col})"] = f"{agg_name}({col})"
    if keys:
        out = df.groupby(keys).agg(combine).reset_index()
    else:
        out = df.agg(combine).to_frame().T
    for agg_name, col in aggs:
        if agg_name == "mean":
            out[f"mean({col})"] = out[f"sum({col})"] / out[f"count({col})"]
            out = out.drop(columns=[f"sum({col})", f"count({col})"])
    return pa.Table.from_pandas(out, preserve_index=False)


def _all_to_all(op: L.LogicalOp, refs: list) -> list:
    """Plan the barrier stage; returns output refs (already submitted)."""
    if isinstance(op, (L.Repartition, L.RandomShuffle)):
        n = op.num_blocks if isinstance(op, L.Repartition) \
            else max(1, len(refs))
        seed = getattr(op, "seed", None)
        if isinstance(op, L.RandomShuffle):
            seed = seed if seed is not None else 0xC0FFEE
        if not refs:
            return []
        parts = [_split_block.options(num_returns=n).remote(
            r, n, None, None if seed is None else seed + i)
            for i, r in enumerate(refs)]
        # parts[i] is a list of n refs (num_returns=n)
        cols = list(zip(*[p if isinstance(p, list) else [p] for p in parts]))
        return [_concat_blocks.remote(*col) for col in cols]
    if isinstance(op, L.Sort):
        # Distributed range-partitioned sort (ray: sort_task_spec.py:91
        # sample_boundaries, :149 map/reduce): sample each block's keys,
        # cut the key space into len(refs) ranges at the sampled
        # quantiles, partition every block per range, merge each range
        # independently.  No single O(dataset) merge task.
        import numpy as np

        if not refs:
            return []
        n = len(refs)
        if n == 1:
            return [_merge_sorted.remote(op.key, op.descending, refs[0])]
        samples = ray_tpu.get(
            [_sample_block.remote(r, op.key, 64) for r in refs])
        allv = np.sort(np.concatenate([s for s in samples if len(s)])
                       if any(len(s) for s in samples) else np.array([0]))
        qs = np.linspace(0, 1, n + 1)[1:-1]
        # Positional quantiles: dtype-agnostic (strings sort too).
        boundaries = list(allv[(qs * (len(allv) - 1)).astype(int)])
        parts = [_range_partition.options(num_returns=n).remote(
            r, op.key, op.descending, boundaries) for r in refs]
        cols = list(zip(*[p if isinstance(p, list) else [p]
                          for p in parts]))
        return [_merge_sorted.remote(op.key, op.descending, *col)
                for col in cols]
    if isinstance(op, L.Aggregate):
        partials = [_partial_agg.remote(r, op.keys, op.aggs) for r in refs]
        if not op.keys or len(refs) <= 1:
            # Global (keyless) aggregate: partials are single rows —
            # one tiny combine.
            return [_final_agg.remote(op.keys, op.aggs, *partials)]
        # Keyed groupby: hash-partition the partials by key so each
        # reducer combines only its key range — no single task holds the
        # whole key space (ray: hash shuffle in push-based aggregate).
        n = len(refs)
        parts = [_hash_partition_rows.options(num_returns=n).remote(
            p, op.keys, n) for p in partials]
        cols = list(zip(*[p if isinstance(p, list) else [p]
                          for p in parts]))
        return [_final_agg.remote(op.keys, op.aggs, *col) for col in cols]
    raise TypeError(f"unknown all-to-all op {op}")


# ------------------------------------------------------------- executor
class _ResourceManager:
    """Bytes-aware backpressure for the grant loop (ray:
    data/_internal/execution/resource_manager.py:25 reservation scheme +
    concurrency_cap_backpressure_policy.py).

    Block sizes come free from the owner table (`CoreWorker.object_sizes`
    — learned at task fulfillment, no payload fetch).  Each live
    streaming operator is reserved an equal share of the memory budget;
    an operator may not launch while its pending footprint (downstream
    input queue it feeds + an average-size estimate for its in-flight
    tasks) exceeds its share.  A progress escape hatch always admits an
    operator whose downstream queue is empty and which has nothing in
    flight, so a single block larger than the share cannot wedge the
    pipeline."""

    def __init__(self, ops: list[PhysicalOp], budget: int):
        self.ops = ops
        self.budget = budget
        self.sizes: dict[Any, int] = {}
        self.avg: dict[int, float] = {}
        self._counts: dict[int, int] = {}
        # Per-op input-queue byte high-water mark (observability + tests).
        self.hwm: dict[int, int] = {}

    def refresh(self) -> None:
        from ray_tpu.experimental import object_sizes
        from ray_tpu.object_ref import ObjectRef

        live: dict[Any, int] = {}
        unknown: list = []
        for i, op in enumerate(self.ops):
            for q in (op.outq, op.inq):
                for r in q:
                    if not isinstance(r, ObjectRef):
                        continue
                    if r in self.sizes:
                        live[r] = self.sizes[r]
                    else:
                        unknown.append((i, r))
        if unknown:
            try:
                got = object_sizes([r for _, r in unknown])
            except Exception:  # noqa: BLE001 - not initialized
                return
            for (i, r), sz in zip(unknown, got):
                if sz is None:
                    continue
                live[r] = sz
                # i-th op's inq blocks were produced by op i-1.
                prod = i - 1 if r in self.ops[i].inq else i
                if prod >= 0:
                    c = self._counts.get(prod, 0)
                    self.avg[prod] = (self.avg.get(prod, 0.0) * c + sz) \
                        / (c + 1)
                    self._counts[prod] = c + 1
        self.sizes = live
        for i, op in enumerate(self.ops):
            b = self._queue_bytes(op)
            if b > self.hwm.get(i, 0):
                self.hwm[i] = b

    def _queue_bytes(self, op: PhysicalOp) -> int:
        return sum(self.sizes.get(r, 0) for r in op.inq)

    def admit(self, idx: int) -> bool:
        op = self.ops[idx]
        if isinstance(op, (AllToAllOp, LimitOp)):
            return True          # barriers/limits: memory is inherent
        n_live = sum(1 for o in self.ops
                     if not o.done and not isinstance(o, (AllToAllOp,
                                                          LimitOp))) or 1
        share = self.budget / n_live
        nxt = self.ops[idx + 1] if idx + 1 < len(self.ops) else None
        downstream = self._queue_bytes(nxt) if nxt is not None else 0
        if idx not in self._counts:
            # No output-size knowledge yet: conservative ramp (ray:
            # concurrency caps start low and grow) — the first completed
            # block teaches the average and lifts this.
            return len(op.inflight) < 2
        est = self.avg.get(idx, 0.0)
        pending = downstream + len(op.inflight) * est
        if pending + est <= share:
            return True
        return not op.inflight and downstream == 0

    def pending_bytes(self, idx: int) -> int:
        nxt = self.ops[idx + 1] if idx + 1 < len(self.ops) else None
        return int((self._queue_bytes(nxt) if nxt is not None else 0)
                   + len(self.ops[idx].inflight)
                   * self.avg.get(idx, 0.0))


def plan_physical(plan: L.ExecutionPlan,
                  max_tasks: int = DEFAULT_MAX_TASKS) -> list[PhysicalOp]:
    ops = L.fuse_row_ops(plan.ops)
    phys: list[PhysicalOp] = []
    for op in ops:
        if isinstance(op, L.Read):
            phys.append(InputOp(op.tasks, max_tasks))
        elif isinstance(op, L.MapBatches) and op.compute == "actors":
            phys.append(ActorMapOp(op))
        elif isinstance(op, (L.MapBatches, L.MapRows, L.Filter, L.FlatMap)):
            phys.append(TaskMapOp(op, max_tasks))
        elif isinstance(op, (L.Repartition, L.RandomShuffle, L.Sort,
                             L.Aggregate)):
            phys.append(AllToAllOp(op))
        elif isinstance(op, L.Limit):
            phys.append(LimitOp(op.n))
        elif isinstance(op, L.Union):
            raise NotImplementedError("union handled at Dataset level")
        else:
            raise TypeError(f"cannot plan {op}")
    return phys


class StreamingExecutor:
    def __init__(self, plan: L.ExecutionPlan,
                 max_tasks: int | None = None,
                 memory_budget: int | None = None):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self.ops = plan_physical(
            plan, ctx.max_tasks_per_op if max_tasks is None else max_tasks)
        self.rm = _ResourceManager(
            self.ops,
            ctx.memory_budget if memory_budget is None else memory_budget)

    def execute(self) -> Iterator[Any]:
        """Yield output block refs as they become available."""
        import time as _t

        ops = self.ops
        if not ops:
            return
        while True:
            progressed = False
            # 1. harvest + propagate
            for i, op in enumerate(ops):
                before = len(op.outq)
                op.harvest()
                progressed |= len(op.outq) != before
                if op.done and op.stat_finished is None:
                    op.stat_finished = _t.monotonic()
                if i + 1 < len(ops):
                    nxt = ops[i + 1]
                    while op.outq:
                        nxt.add_input(op.outq.popleft())
                        op.stat_blocks_out += 1
                        progressed = True
                    if op.done and not nxt.in_done:
                        nxt.mark_input_done()
                        progressed = True
            # 2. emit from the tail
            tail = ops[-1]
            while tail.outq:
                progressed = True
                tail.stat_blocks_out += 1
                yield tail.outq.popleft()
            if tail.done:
                if tail.stat_finished is None:
                    tail.stat_finished = _t.monotonic()
                return
            # 3. grant launches, most-downstream first (backpressure);
            #    the resource manager gates on per-operator memory share.
            self.rm.refresh()
            for i in reversed(range(len(ops))):
                op = ops[i]
                while op.can_launch() and self.rm.admit(i):
                    if op.stat_started is None:
                        op.stat_started = _t.monotonic()
                    op.launch_one()
                    op.stat_launched += 1
                    progressed = True
            if not progressed:
                _t.sleep(0.005)

    def stats(self) -> str:
        """Per-operator summary of the last execute() (ray:
        DatasetStats string — operator name, task count, blocks emitted,
        wall clock from first launch to completion)."""
        import time as _t

        lines = []
        for op in self.ops:
            if op.stat_started is None:
                wall = 0.0
            else:
                end = op.stat_finished if op.stat_finished is not None \
                    else _t.monotonic()
                wall = end - op.stat_started
            lines.append(
                f"{op.name}: tasks={op.stat_launched} "
                f"blocks_out={op.stat_blocks_out} wall={wall:.3f}s "
                f"pending={self.rm.pending_bytes(self.ops.index(op))}B "
                f"{'done' if op.done else 'running'}")
        return "\n".join(lines)
