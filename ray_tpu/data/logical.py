"""Logical plan + optimizer (analog of ray:
python/ray/data/_internal/logical/ operators + planner rules).

A Dataset holds an immutable chain of logical ops; consumption plans it
into physical operators (executor.py).  The one optimizer rule that pays
for itself is operator fusion: adjacent row/batch transforms collapse into
a single task per block (ray: planner fuses Map chains the same way).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable


@dataclasses.dataclass
class LogicalOp:
    name: str = dataclasses.field(default="", init=False)


@dataclasses.dataclass
class Read(LogicalOp):
    tasks: list        # list[ReadTask]
    # Source paths for Dataset.input_files (file-based readers only).
    input_files: list | None = None

    def __post_init__(self):
        self.name = "Read"


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Callable | type
    batch_size: int | None = None
    batch_format: str = "numpy"
    compute: str = "tasks"           # "tasks" | "actors"
    concurrency: int | tuple | None = None
    fn_args: tuple = ()
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    fn_constructor_args: tuple = ()
    num_tpus: float = 0.0
    num_cpus: float | None = None

    def __post_init__(self):
        self.name = "MapBatches"


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable

    def __post_init__(self):
        self.name = "Map"


@dataclasses.dataclass
class Filter(LogicalOp):
    fn: Callable

    def __post_init__(self):
        self.name = "Filter"


@dataclasses.dataclass
class FlatMap(LogicalOp):
    fn: Callable

    def __post_init__(self):
        self.name = "FlatMap"


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int

    def __post_init__(self):
        self.name = "Repartition"


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: int | None = None

    def __post_init__(self):
        self.name = "RandomShuffle"


@dataclasses.dataclass
class Sort(LogicalOp):
    key: str
    descending: bool = False

    def __post_init__(self):
        self.name = "Sort"


@dataclasses.dataclass
class Aggregate(LogicalOp):
    keys: list[str]
    aggs: list[tuple[str, str]]      # (agg_name, column)

    def __post_init__(self):
        self.name = "Aggregate"


@dataclasses.dataclass
class Limit(LogicalOp):
    n: int

    def __post_init__(self):
        self.name = "Limit"


@dataclasses.dataclass
class Union(LogicalOp):
    others: list        # list[ExecutionPlan]

    def __post_init__(self):
        self.name = "Union"


@dataclasses.dataclass
class Zip(LogicalOp):
    other: Any          # ExecutionPlan

    def __post_init__(self):
        self.name = "Zip"


class ExecutionPlan:
    def __init__(self, ops: list[LogicalOp]):
        self.ops = ops

    def with_op(self, op: LogicalOp) -> "ExecutionPlan":
        return ExecutionPlan([*self.ops, op])

    def __repr__(self):
        return " -> ".join(op.name for op in self.ops)


ROW_OPS = (MapRows, Filter, FlatMap)


def fuse_row_ops(ops: list[LogicalOp]) -> list[LogicalOp]:
    """Collapse runs of row-level transforms into one fused op so each
    block round-trips through a worker exactly once."""
    out: list[LogicalOp] = []
    run: list[LogicalOp] = []

    def flush():
        if not run:
            return
        if len(run) == 1:
            out.append(run[0])
        else:
            fns = [(type(op).__name__, op.fn) for op in run]

            def fused(row, fns=fns):
                rows = [row]
                for kind, fn in fns:
                    nxt = []
                    for r in rows:
                        if kind == "MapRows":
                            nxt.append(fn(r))
                        elif kind == "Filter":
                            if fn(r):
                                nxt.append(r)
                        else:               # FlatMap
                            nxt.extend(fn(r))
                    rows = nxt
                return rows

            op = FlatMap(fused)
            op.name = "Fused[" + ",".join(
                type(o).__name__ for o in run) + "]"
            out.append(op)
        run.clear()

    for op in ops:
        if isinstance(op, ROW_OPS):
            run.append(op)
        else:
            flush()
            out.append(op)
    flush()
    return out
