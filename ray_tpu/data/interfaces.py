"""Custom source/sink protocol + execution knobs (ray:
python/ray/data/datasource/datasource.py Datasource/Datasink,
data/_internal/execution/interfaces/execution_options.py).

Redesigned small: a Datasource yields ReadTasks (the same plain
zero-arg callables every built-in reader produces), a Datasink gets one
`write(block)` call per block inside a task; ExecutionOptions /
ExecutionResources parameterize the streaming executor's budget through
DataContext rather than a per-run options object.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Iterator


class Datasource:
    """Subclass + implement get_read_tasks (ray: Datasource.get_read_tasks);
    each task is a zero-arg callable yielding blocks."""

    def get_read_tasks(self, parallelism: int) -> list:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> int | None:
        return None


class Datasink:
    """Subclass + implement write (ray: Datasink): called once per block
    inside a write task; on_write_complete runs on the driver after all
    blocks land."""

    def write(self, block) -> Any:
        raise NotImplementedError

    def on_write_start(self) -> None:  # noqa: B027
        pass

    def on_write_complete(self, write_results: list) -> None:  # noqa: B027
        pass


@dataclasses.dataclass
class ExecutionResources:
    cpu: float | None = None
    gpu: float | None = None
    object_store_memory: float | None = None


@dataclasses.dataclass
class ExecutionOptions:
    resource_limits: ExecutionResources = dataclasses.field(
        default_factory=ExecutionResources)
    locality_with_output: bool = False
    preserve_order: bool = False
    verbose_progress: bool = False


class ActorPoolStrategy:
    """map_batches compute strategy (ray: ActorPoolStrategy): stateful
    UDFs run in a pool of actors sized [min_size, max_size]."""

    def __init__(self, *, size: int | None = None,
                 min_size: int | None = None,
                 max_size: int | None = None):
        if size is not None:
            min_size = max_size = size
        self.min_size = min_size or 1
        self.max_size = max_size or (min_size or 1)
        if self.max_size < self.min_size:
            raise ValueError("max_size < min_size")
