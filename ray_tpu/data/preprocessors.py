"""Standard preprocessors (ray: python/ray/data/preprocessors/).

Same public surface as the reference's __init__ exports — scalers
(Standard/MinMax/MaxAbs/Robust), encoders (OneHot/MultiHot/Ordinal/
Label), SimpleImputer, discretizers, Normalizer, Tokenizer, vectorizers,
FeatureHasher, PowerTransformer, Concatenator, Chain — re-implemented on
the two-phase aggregate_blocks fit (preprocessor.py) and numpy batches.
TorchVisionPreprocessor is intentionally absent (no torchvision in the
image; jax image pipelines use map_batches directly).
"""
from __future__ import annotations

import collections
import zlib
from typing import Callable

import numpy as np

from ray_tpu.data.preprocessor import Preprocessor, aggregate_blocks

__all__ = [
    "Chain", "Concatenator", "CountVectorizer", "CustomKBinsDiscretizer",
    "FeatureHasher", "HashingVectorizer", "LabelEncoder", "MaxAbsScaler",
    "MinMaxScaler", "MultiHotEncoder", "Normalizer", "OneHotEncoder",
    "OrdinalEncoder", "PowerTransformer", "RobustScaler", "SimpleImputer",
    "StandardScaler", "Tokenizer", "UniformKBinsDiscretizer",
]


# ---------------------------------------------------------------- moments
def _moment_partial(columns):
    def partial(batch):
        out = {}
        for c in columns:
            v = np.asarray(batch[c], dtype=np.float64)
            m = v[~np.isnan(v)]
            out[c] = (m.size, m.sum(), (m * m).sum(),
                      m.min() if m.size else np.inf,
                      m.max() if m.size else -np.inf,
                      np.abs(m).max() if m.size else 0.0)
        return out

    return partial


def _moment_combine(a, b):
    return {c: (a[c][0] + b[c][0], a[c][1] + b[c][1], a[c][2] + b[c][2],
                min(a[c][3], b[c][3]), max(a[c][4], b[c][4]),
                max(a[c][5], b[c][5]))
            for c in a}


class _MomentFitMixin:
    """Shared fit: per-column (count, sum, sumsq, min, max, absmax)."""

    def _fit(self, ds) -> None:
        stats = aggregate_blocks(ds, _moment_partial(self.columns),
                                 _moment_combine)
        self.stats_ = {}
        for c, (n, s, ss, mn, mx, am) in stats.items():
            mean = s / n if n else 0.0
            var = max(ss / n - mean * mean, 0.0) if n else 0.0
            self.stats_[c] = {"count": n, "mean": mean,
                              "std": float(np.sqrt(var)),
                              "min": mn, "max": mx, "abs_max": am}


class StandardScaler(_MomentFitMixin, Preprocessor):
    """x -> (x - mean) / std (ray: preprocessors/scaler.py StandardScaler)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            denom = st["std"] or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - st["mean"]) / denom
        return batch


class MinMaxScaler(_MomentFitMixin, Preprocessor):
    """x -> (x - min) / (max - min) (ray: scaler.py MinMaxScaler)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            span = (st["max"] - st["min"]) or 1.0
            batch[c] = (np.asarray(batch[c], np.float64) - st["min"]) / span
        return batch


class MaxAbsScaler(_MomentFitMixin, Preprocessor):
    """x -> x / max|x| (ray: scaler.py MaxAbsScaler)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _transform_batch(self, batch):
        for c in self.columns:
            batch[c] = (np.asarray(batch[c], np.float64)
                        / (self.stats_[c]["abs_max"] or 1.0))
        return batch


class RobustScaler(Preprocessor):
    """x -> (x - median) / IQR (ray: scaler.py RobustScaler).

    Quantiles are exact: the fit pulls ONLY the scaled columns to the
    driver (a [n_rows] float per column) — fine at preprocessor-fit
    scale; the reference approximates through its aggregate layer.
    """

    def __init__(self, columns: list[str],
                 quantile_range: tuple[float, float] = (0.25, 0.75)):
        self.columns = list(columns)
        self.quantile_range = quantile_range

    def _fit(self, ds) -> None:
        lo_q, hi_q = self.quantile_range
        arrs = ds.select_columns(self.columns).to_numpy()
        self.stats_ = {}
        for c in self.columns:
            v = np.asarray(arrs[c], np.float64)
            v = v[~np.isnan(v)]
            lo, med, hi = np.quantile(v, [lo_q, 0.5, hi_q])
            self.stats_[c] = {"median": med, "iqr": hi - lo}

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            batch[c] = ((np.asarray(batch[c], np.float64) - st["median"])
                        / (st["iqr"] or 1.0))
        return batch


# ----------------------------------------------------------- value counts
def _value_counts(columns):
    def partial(batch):
        out = {}
        for c in columns:
            v = np.asarray(batch[c])
            if v.dtype.kind == "f":
                # Drop NaNs: nan != nan, so each one would count as its
                # OWN category (hash(nan) is id-based) — a 10%-missing
                # float column would bloat the vocabulary by one entry
                # per missing row.
                v = v[~np.isnan(v)]
            out[c] = collections.Counter(
                x for x in v.tolist() if x is not None)
        return out

    return partial


def _counts_combine(a, b):
    return {c: a[c] + b[c] for c in a}


def _sorted_uniques(counter) -> list:
    return sorted(counter.keys(), key=lambda v: (str(type(v)), v))


class OrdinalEncoder(Preprocessor):
    """Category -> its rank among the sorted fitted values (ray:
    encoder.py OrdinalEncoder).  Unseen values encode as -1."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        counts = aggregate_blocks(ds, _value_counts(self.columns),
                                  _counts_combine)
        self.stats_ = {c: {v: i for i, v in
                           enumerate(_sorted_uniques(counts[c]))}
                       for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            table = self.stats_[c]
            batch[c] = np.array([table.get(v, -1)
                                 for v in np.asarray(batch[c]).tolist()],
                                np.int64)
        return batch


class LabelEncoder(OrdinalEncoder):
    """OrdinalEncoder for the single label column (ray: encoder.py
    LabelEncoder)."""

    def __init__(self, label_column: str):
        super().__init__([label_column])
        self.label_column = label_column

    def inverse_transform_batch(self, batch):
        inv = {i: v for v, i in self.stats_[self.label_column].items()}
        batch = dict(batch)
        batch[self.label_column] = np.array(
            [inv.get(int(i)) for i in np.asarray(batch[self.label_column])])
        return batch


class OneHotEncoder(Preprocessor):
    """Category column -> one 0/1 column per category, named
    `{column}_{value}`; the source column is dropped (ray: encoder.py
    OneHotEncoder semantics)."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        counts = aggregate_blocks(ds, _value_counts(self.columns),
                                  _counts_combine)
        self.stats_ = {c: _sorted_uniques(counts[c]) for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            vals = np.asarray(batch.pop(c)).tolist()
            for cat in self.stats_[c]:
                batch[f"{c}_{cat}"] = np.array(
                    [1 if v == cat else 0 for v in vals], np.int8)
        return batch


class MultiHotEncoder(Preprocessor):
    """List column -> multi-hot count vector over the fitted vocabulary
    (ray: encoder.py MultiHotEncoder).  Output is a [n, n_categories]
    tensor column under the same name."""

    def __init__(self, columns: list[str]):
        self.columns = list(columns)

    def _fit(self, ds) -> None:
        def partial(batch):
            return {c: collections.Counter(
                v for row in np.asarray(batch[c], dtype=object)
                for v in row) for c in self.columns}

        counts = aggregate_blocks(ds, partial, _counts_combine)
        self.stats_ = {c: {v: i for i, v in
                           enumerate(_sorted_uniques(counts[c]))}
                       for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            table = self.stats_[c]
            rows = np.asarray(batch[c], dtype=object)
            out = np.zeros((len(rows), len(table)), np.int64)
            for i, row in enumerate(rows):
                for v in row:
                    j = table.get(v)
                    if j is not None:
                        out[i, j] += 1
            batch[c] = out
        return batch


class SimpleImputer(Preprocessor):
    """Fill missing values: strategy mean | most_frequent | constant
    (ray: imputer.py SimpleImputer)."""

    def __init__(self, columns: list[str], strategy: str = "mean",
                 fill_value=None):
        if strategy not in ("mean", "most_frequent", "constant"):
            raise ValueError(f"unknown strategy {strategy!r}")
        if strategy == "constant" and fill_value is None:
            raise ValueError("strategy='constant' needs fill_value")
        self.columns = list(columns)
        self.strategy = strategy
        self.fill_value = fill_value
        self._is_fittable = strategy != "constant"

    def _fit(self, ds) -> None:
        if self.strategy == "constant":
            return          # nothing to learn; fill_value is the state
        if self.strategy == "mean":
            stats = aggregate_blocks(ds, _moment_partial(self.columns),
                                     _moment_combine)
            self.stats_ = {c: (s[1] / s[0] if s[0] else 0.0)
                           for c, s in stats.items()}
        else:  # most_frequent
            counts = aggregate_blocks(ds, _value_counts(self.columns),
                                      _counts_combine)
            for c in self.columns:
                if not counts[c]:
                    raise ValueError(
                        f"column {c!r} has no non-missing values; "
                        "most_frequent cannot be fit (use "
                        "strategy='constant')")
            self.stats_ = {c: counts[c].most_common(1)[0][0]
                           for c in self.columns}

    def _transform_batch(self, batch):
        for c in self.columns:
            fill = (self.fill_value if self.strategy == "constant"
                    else self.stats_[c])
            v = np.asarray(batch[c])
            if v.dtype.kind == "f":
                batch[c] = np.where(np.isnan(v), fill, v)
            else:
                batch[c] = np.array(
                    [fill if x is None else x for x in v.tolist()])
        return batch


# ------------------------------------------------------------ discretize
class UniformKBinsDiscretizer(_MomentFitMixin, Preprocessor):
    """Equal-width binning over the fitted [min, max] (ray:
    discretizer.py UniformKBinsDiscretizer)."""

    def __init__(self, columns: list[str], bins: int):
        self.columns = list(columns)
        self.bins = bins

    def _transform_batch(self, batch):
        for c in self.columns:
            st = self.stats_[c]
            edges = np.linspace(st["min"], st["max"], self.bins + 1)
            batch[c] = np.clip(
                np.digitize(np.asarray(batch[c], np.float64),
                            edges[1:-1]), 0, self.bins - 1).astype(np.int64)
        return batch


class CustomKBinsDiscretizer(Preprocessor):
    """Binning with caller-provided edges (ray: discretizer.py
    CustomKBinsDiscretizer) — stateless."""

    _is_fittable = False

    def __init__(self, columns: list[str], bin_edges: dict[str, list]):
        self.columns = list(columns)
        self.bin_edges = bin_edges

    def _transform_batch(self, batch):
        for c in self.columns:
            edges = np.asarray(self.bin_edges[c], np.float64)
            batch[c] = np.digitize(np.asarray(batch[c], np.float64),
                                   edges[1:-1]).astype(np.int64)
        return batch


# ------------------------------------------------------------- stateless
class Normalizer(Preprocessor):
    """Row-wise vector normalization of tensor columns: l1 | l2 | max
    (ray: normalizer.py)."""

    _is_fittable = False

    def __init__(self, columns: list[str], norm: str = "l2"):
        if norm not in ("l1", "l2", "max"):
            raise ValueError(f"unknown norm {norm!r}")
        self.columns = list(columns)
        self.norm = norm

    def _transform_batch(self, batch):
        for c in self.columns:
            v = np.asarray(batch[c], np.float64)
            if self.norm == "l1":
                d = np.abs(v).sum(axis=-1, keepdims=True)
            elif self.norm == "l2":
                d = np.sqrt((v * v).sum(axis=-1, keepdims=True))
            else:
                d = np.abs(v).max(axis=-1, keepdims=True)
            batch[c] = v / np.where(d == 0, 1.0, d)
        return batch


class PowerTransformer(Preprocessor):
    """Box-Cox / Yeo-Johnson with a caller-chosen power (ray:
    transformer.py PowerTransformer — also takes `power` explicitly)."""

    _is_fittable = False

    def __init__(self, columns: list[str], power: float,
                 method: str = "yeo-johnson"):
        if method not in ("yeo-johnson", "box-cox"):
            raise ValueError(f"unknown method {method!r}")
        self.columns = list(columns)
        self.power = power
        self.method = method

    def _transform_batch(self, batch):
        lam = self.power
        for c in self.columns:
            v = np.asarray(batch[c], np.float64)
            if self.method == "box-cox":
                batch[c] = (np.log(v) if lam == 0
                            else (np.power(v, lam) - 1) / lam)
            else:
                pos = v >= 0
                if lam == 0:
                    a = np.log1p(np.where(pos, v, 0))
                else:
                    a = (np.power(np.where(pos, v, 0) + 1, lam) - 1) / lam
                if lam == 2:
                    b = -np.log1p(np.where(pos, 0, -v))
                else:
                    b = -((np.power(np.where(pos, 0, -v) + 1, 2 - lam) - 1)
                          / (2 - lam))
                batch[c] = np.where(pos, a, b)
        return batch


class Concatenator(Preprocessor):
    """Merge numeric columns into one [n, d] tensor column (ray:
    concatenator.py) — the device-feed shape for jax/torch batches."""

    _is_fittable = False

    def __init__(self, columns: list[str],
                 output_column_name: str = "concat",
                 dtype=np.float32, drop: bool = True):
        self.columns = list(columns)
        self.output_column_name = output_column_name
        self.dtype = dtype
        self.drop = drop

    def _transform_batch(self, batch):
        parts = []
        for c in self.columns:
            v = np.asarray(batch[c], self.dtype)
            parts.append(v[:, None] if v.ndim == 1 else
                         v.reshape(v.shape[0], -1))
            if self.drop:
                batch.pop(c)
        batch[self.output_column_name] = np.concatenate(parts, axis=1)
        return batch


class Tokenizer(Preprocessor):
    """String column -> list-of-tokens column (ray: tokenizer.py);
    default tokenization is whitespace split."""

    _is_fittable = False

    def __init__(self, columns: list[str],
                 tokenization_fn: Callable[[str], list] | None = None):
        self.columns = list(columns)
        self.tokenization_fn = tokenization_fn or str.split

    def _transform_batch(self, batch):
        for c in self.columns:
            batch[c] = np.array(
                [self.tokenization_fn(str(v))
                 for v in np.asarray(batch[c]).tolist()], dtype=object)
        return batch


def _stable_hash(token: str, mod: int) -> int:
    """Deterministic across processes (unlike builtin str hash, which is
    salted per interpreter — workers would disagree)."""
    return zlib.crc32(token.encode()) % mod


class FeatureHasher(Preprocessor):
    """Hash token-count dict columns into a fixed-width vector (ray:
    hasher.py FeatureHasher): input columns hold {token: count} dicts or
    token lists; output is one [n, num_features] tensor column."""

    _is_fittable = False

    def __init__(self, columns: list[str], num_features: int,
                 output_column_name: str = "hashed_features"):
        self.columns = list(columns)
        self.num_features = num_features
        self.output_column_name = output_column_name

    def _transform_batch(self, batch):
        n = len(next(iter(batch.values())))
        out = np.zeros((n, self.num_features), np.float64)
        for c in self.columns:
            rows = np.asarray(batch.pop(c), dtype=object)
            for i, row in enumerate(rows):
                items = (row.items() if isinstance(row, dict)
                         else ((t, 1) for t in row))
                for tok, cnt in items:
                    out[i, _stable_hash(str(tok), self.num_features)] += cnt
        batch[self.output_column_name] = out
        return batch


class HashingVectorizer(Preprocessor):
    """Stateless bag-of-words: tokenize + hash each string column into a
    [n, num_features] count vector under the same name (ray:
    vectorizer.py HashingVectorizer)."""

    _is_fittable = False

    def __init__(self, columns: list[str], num_features: int,
                 tokenization_fn: Callable[[str], list] | None = None):
        self.columns = list(columns)
        self.num_features = num_features
        self.tokenization_fn = tokenization_fn or str.split

    def _transform_batch(self, batch):
        for c in self.columns:
            vals = np.asarray(batch[c]).tolist()
            out = np.zeros((len(vals), self.num_features), np.int64)
            for i, v in enumerate(vals):
                for tok in self.tokenization_fn(str(v)):
                    out[i, _stable_hash(tok, self.num_features)] += 1
            batch[c] = out
        return batch


class CountVectorizer(Preprocessor):
    """Bag-of-words over a fitted vocabulary; optional max_features keeps
    the most frequent tokens (ray: vectorizer.py CountVectorizer)."""

    def __init__(self, columns: list[str],
                 tokenization_fn: Callable[[str], list] | None = None,
                 max_features: int | None = None):
        self.columns = list(columns)
        self.tokenization_fn = tokenization_fn or str.split
        self.max_features = max_features

    def _fit(self, ds) -> None:
        fn = self.tokenization_fn

        def partial(batch):
            return {c: collections.Counter(
                tok for v in np.asarray(batch[c]).tolist()
                for tok in fn(str(v))) for c in self.columns}

        counts = aggregate_blocks(ds, partial, _counts_combine)
        self.stats_ = {}
        for c in self.columns:
            items = counts[c].most_common(self.max_features)
            self.stats_[c] = {tok: i for i, (tok, _) in
                              enumerate(sorted(items))}

    def _transform_batch(self, batch):
        for c in self.columns:
            vocab = self.stats_[c]
            vals = np.asarray(batch[c]).tolist()
            out = np.zeros((len(vals), len(vocab)), np.int64)
            for i, v in enumerate(vals):
                for tok in self.tokenization_fn(str(v)):
                    j = vocab.get(tok)
                    if j is not None:
                        out[i, j] += 1
            batch[c] = out
        return batch


class Chain(Preprocessor):
    """Sequential composition; fit runs left to right, each stage fitting
    on the previous stages' transform (ray: chain.py Chain)."""

    def __init__(self, *preprocessors: Preprocessor):
        self.preprocessors = list(preprocessors)

    def _fit(self, ds) -> None:
        for p in self.preprocessors[:-1]:
            ds = p.fit_transform(ds)
        if self.preprocessors:
            self.preprocessors[-1].fit(ds)

    def transform(self, ds):
        self._check_fitted()
        for p in self.preprocessors:
            ds = p.transform(ds)
        return ds

    def transform_batch(self, batch: dict) -> dict:
        self._check_fitted()
        for p in self.preprocessors:
            batch = p.transform_batch(batch)
        return batch

    def _check_fitted(self) -> None:
        for p in self.preprocessors:
            p._check_fitted()
