"""Blocks: the unit of data movement (analog of ray Data's block =
Arrow table in plasma; ray: python/ray/data/block.py BlockAccessor).

Canonical block = pyarrow.Table (zero-copy through the shm object store);
map_batches views it as numpy / pandas / pyarrow per `batch_format`.
"""
from __future__ import annotations

from typing import Any, Iterable

import numpy as np
import pyarrow as pa

Block = pa.Table


def _to_table(data: Any) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        cols = {}
        meta = {}
        for k, v in data.items():
            arr = np.asarray(v)
            if arr.ndim > 1:
                # Tensor column: fixed-size-list of flattened rows, with
                # the per-row shape in schema metadata so to_numpy
                # restores [n, *shape] instead of [n, prod(shape)].
                flat = arr.reshape(arr.shape[0], -1)
                cols[k] = pa.FixedSizeListArray.from_arrays(
                    pa.array(flat.ravel()), flat.shape[1])
                if arr.ndim > 2:
                    import json as _json

                    meta[f"tensor:{k}"] = _json.dumps(arr.shape[1:])
                continue
            cols[k] = pa.array(arr)
        t = pa.table(cols)
        if meta:
            t = t.replace_schema_metadata(
                {**(t.schema.metadata or {}),
                 **{k.encode(): v.encode() for k, v in meta.items()}})
        return t
    try:
        import pandas as pd

        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:
        pass
    raise TypeError(f"cannot convert {type(data)} to a block")


def _rows_to_table(rows: list) -> pa.Table:
    if rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return _to_table({k: [r[k] for r in rows] for k in keys})
    return _to_table({"item": rows})


class BlockAccessor:
    """Uniform view over a block (ray: BlockAccessor.for_block)."""

    def __init__(self, block: pa.Table):
        self.block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(_to_table(block))

    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self) -> pa.Schema:
        return self.block.schema

    def slice(self, start: int, end: int) -> pa.Table:
        return self.block.slice(start, end - start)

    def to_numpy(self) -> dict[str, np.ndarray]:
        out = {}
        for name in self.block.column_names:
            col = self.block.column(name)
            if pa.types.is_fixed_size_list(col.type):
                width = col.type.list_size
                flat = col.combine_chunks().flatten().to_numpy(
                    zero_copy_only=False)
                arr = flat.reshape(-1, width)
                meta = self.block.schema.metadata or {}
                shape_b = meta.get(f"tensor:{name}".encode())
                if shape_b is not None:
                    import json as _json

                    arr = arr.reshape(-1, *_json.loads(shape_b))
                out[name] = arr
            else:
                out[name] = col.to_numpy(zero_copy_only=False)
        return out

    def to_pandas(self):
        return self.block.to_pandas()

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "default", None):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.block
        raise ValueError(f"unknown batch_format {batch_format!r}")

    def iter_rows(self) -> Iterable[dict]:
        cols = self.to_numpy()
        names = list(cols)
        for i in range(self.num_rows()):
            yield {k: cols[k][i] for k in names}

    @staticmethod
    def concat(blocks: list[pa.Table]) -> pa.Table:
        blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
        return pa.concat_tables(blocks, promote_options="default")

    @staticmethod
    def empty() -> pa.Table:
        return pa.table({})
