"""DataContext: per-process execution knobs (ray:
python/ray/data/context.py DataContext.get_current).

Holds the streaming executor's resource limits; tests and users tune
these without threading parameters through every Dataset call.
"""
from __future__ import annotations

DEFAULT_MEMORY_BUDGET = 256 * 1024 * 1024
DEFAULT_MAX_TASKS = 8


class DataContext:
    _current: "DataContext | None" = None

    def __init__(self) -> None:
        # Byte budget the resource manager splits across live operators.
        self.memory_budget: int = DEFAULT_MEMORY_BUDGET
        # Per-operator concurrent task cap.
        self.max_tasks_per_op: int = DEFAULT_MAX_TASKS

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._current is None:
            cls._current = DataContext()
        return cls._current
