"""Dataset: the lazy, streaming public API.

Analog of ray: python/ray/data/dataset.py:139 (Dataset), with the same
contract: transformations are lazy logical ops; consumption plans and
streams through the executor (SURVEY §3.6); blocks live in the object
store, not the driver.

TPU-native: `streaming_split` feeds per-train-worker shards through a
coordinator actor; `iter_jax_batches` double-buffers into HBM.
"""
from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data import datasource as ds
from ray_tpu.data import logical as L
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.executor import StreamingExecutor
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, plan: L.ExecutionPlan):
        self._plan = plan
        self._materialized: list | None = None   # block refs once computed
        self._union_sources: list | None = None

    def _as_plan(self) -> L.ExecutionPlan:
        """A logical plan view even for materialized/union datasets, so
        every transformation composes (post-union maps, etc.)."""
        if self._plan is not None:
            return self._plan
        self.materialize()
        refs = self._materialized

        def mk(ref):
            def read() -> Iterator:
                yield ray_tpu.get(ref)

            return read

        return L.ExecutionPlan([L.Read([mk(r) for r in refs])])

    # ------------------------------------------------------ transformations
    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._as_plan().with_op(op))

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with(L.MapRows(fn))

    def map_batches(self, fn, *, batch_size: int | None = None,
                    batch_format: str = "numpy", compute: str | None = None,
                    concurrency: int | tuple | None = None,
                    fn_args: tuple = (), fn_kwargs: dict | None = None,
                    fn_constructor_args: tuple = (),
                    num_cpus: float | None = None,
                    num_tpus: float = 0.0) -> "Dataset":
        """fn: batch->batch (callable) or a class (stateful actor UDF,
        compute="actors")."""
        if compute is None:
            compute = "actors" if isinstance(fn, type) else "tasks"
        return self._with(L.MapBatches(
            fn, batch_size=batch_size, batch_format=batch_format,
            compute=compute, concurrency=concurrency, fn_args=fn_args,
            fn_kwargs=fn_kwargs or {},
            fn_constructor_args=fn_constructor_args,
            num_cpus=num_cpus, num_tpus=num_tpus))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with(L.Filter(fn))

    def flat_map(self, fn: Callable[[dict], Iterable[dict]]) -> "Dataset":
        return self._with(L.FlatMap(fn))

    def add_column(self, name: str, fn: Callable[[dict], Any]) -> "Dataset":
        def add(row):
            row = dict(row)
            row[name] = fn(row)
            return row

        return self.map(add)

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(drop)

    def select_columns(self, cols: list[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return self.map_batches(select)

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.Repartition(num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._with(L.RandomShuffle(seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n))

    def groupby(self, key: str | list[str] | None):
        from ray_tpu.data.grouped import GroupedData

        keys = [key] if isinstance(key, str) else (key or [])
        return GroupedData(self, keys)

    def union(self, *others: "Dataset") -> "Dataset":
        plans = [self._as_plan(), *[o._as_plan() for o in others]]
        u = Dataset.__new__(Dataset)
        u._plan = None
        u._materialized = None
        u._union_sources = plans
        return u

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of equal-length datasets."""
        left = self.materialize()._materialized
        right = other.materialize()._materialized

        @ray_tpu.remote
        def zip_blocks(*parts):
            import pyarrow as pa

            half = len(parts) // 2
            lt = BlockAccessor.concat(list(parts[:half]))
            rt = BlockAccessor.concat(list(parts[half:]))
            cols = {**BlockAccessor(lt).to_numpy(),
                    **BlockAccessor(rt).to_numpy()}
            from ray_tpu.data.block import _to_table

            return _to_table(cols)

        ref = zip_blocks.remote(*left, *right)
        out = Dataset.__new__(Dataset)
        out._plan = None
        out._materialized = [ref]
        out._union_sources = None
        return out

    # ------------------------------------------------------------ execution
    def _ref_iter(self) -> Iterator:
        if self._materialized is not None:
            return iter(self._materialized)
        if getattr(self, "_union_sources", None):
            self._executors = []

            def chain():
                for p in self._union_sources:
                    ex = StreamingExecutor(p)
                    self._executors.append(ex)
                    yield from ex.execute()

            return chain()
        ex = StreamingExecutor(self._plan)
        self._executors = [ex]
        return ex.execute()

    def stats(self) -> str:
        """Per-operator execution stats of the most recent run (ray:
        Dataset.stats() backed by data/_internal/stats.py)."""
        exs = getattr(self, "_executors", None)
        if not exs:
            return "(dataset has not been executed yet)"
        return "\n".join(ex.stats() for ex in exs)

    def iterator(self) -> DataIterator:
        return DataIterator(self._ref_iter)

    def materialize(self) -> "Dataset":
        if self._materialized is None:
            self._materialized = list(self._ref_iter())
        return self

    # ----------------------------------------------------------- consumption
    def iter_batches(self, **kw) -> Iterator:
        return self.iterator().iter_batches(**kw)

    def iter_rows(self) -> Iterator[dict]:
        return self.iterator().iter_rows()

    def iter_torch_batches(self, **kw) -> Iterator:
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator:
        return self.iterator().iter_jax_batches(**kw)

    def take(self, n: int = 20) -> list[dict]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(BlockAccessor.for_block(ray_tpu.get(r)).num_rows()
                   for r in self._ref_iter())

    def schema(self):
        for ref in self._ref_iter():
            return BlockAccessor.for_block(ray_tpu.get(ref)).schema()
        return None

    def columns(self) -> list[str]:
        sch = self.schema()
        return list(sch.names) if sch is not None else []

    def num_blocks(self) -> int:
        self.materialize()
        return len(self._materialized)

    def size_bytes(self) -> int:
        return sum(BlockAccessor.for_block(ray_tpu.get(r)).size_bytes()
                   for r in self._ref_iter())

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor.for_block(ray_tpu.get(r)).to_pandas()
                  for r in self._ref_iter()]
        frames = [f for f in frames if not f.empty] or frames[:1]
        return pd.concat(frames, ignore_index=True) if frames \
            else pd.DataFrame()

    def to_numpy(self) -> dict[str, np.ndarray]:
        return self.iterator().materialize_numpy()

    # ---------------------------------------------------------------- split
    def split(self, n: int) -> list["Dataset"]:
        """Materialize and split into n datasets by block round-robin."""
        self.materialize()
        outs = []
        for i in builtins.range(n):
            part = self._materialized[i::n]
            d = Dataset.__new__(Dataset)
            d._plan = None
            d._materialized = part
            d._union_sources = None
            outs.append(d)
        return outs

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> list[DataIterator]:
        """n DataIterators fed round-robin while execution streams
        (ray: Dataset.streaming_split dataset.py:1236 via a coordinator
        actor).  Each split may be consumed from a different process."""
        if self._materialized is not None:
            ops, mat = None, self._materialized
        else:
            ops, mat = self._as_plan().ops, None
        coord = _SplitCoordinator.options(num_cpus=0).remote(ops, mat, n)

        def make_factory(idx: int):
            def refs() -> Iterator:
                while True:
                    ref = ray_tpu.get(coord.next_ref.remote(idx))
                    if ref is None:
                        return
                    yield ref

            return refs

        its = [DataIterator(make_factory(i)) for i in builtins.range(n)]
        for it in its:
            it._coordinator = coord    # keep the actor alive
        return its

    # ---------------------------------------------------------------- write
    def _write(self, path: str, fmt: str) -> None:
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block, idx):
            return ds.write_block(block, path, fmt, idx)

        ray_tpu.get([write_one.remote(r, i) for i, r in enumerate(refs)])

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def write_tfrecords(self, path: str) -> None:
        self._write(path, "tfrecord")

    def __repr__(self):
        if self._materialized is not None:
            return f"MaterializedDataset({len(self._materialized)} blocks)"
        return f"Dataset({self._plan})"


class _SplitCoordinator:
    """Actor running the streaming executor, handing refs to n consumers
    round-robin (ray: StreamSplitDataIterator's coordinator)."""

    def __init__(self, ops, materialized, n: int):
        import collections
        import threading

        self.n = n
        self.queues = [collections.deque() for _ in builtins.range(n)]
        self.done = False
        self.lock = threading.Lock()
        # Pin handed-out refs: this actor owns the blocks, and a consumer
        # may fetch a ref after the local ObjectRef would otherwise be
        # GC'd (owner frees → ObjectLostError at the borrower).
        self._handed: list = []

        def run():
            try:
                if materialized is not None:
                    refs = iter(materialized)
                else:
                    refs = StreamingExecutor(
                        L.ExecutionPlan(ops)).execute()
                for i, ref in enumerate(refs):
                    with self.lock:
                        self.queues[i % n].append(ref)
            finally:
                self.done = True

        threading.Thread(target=run, daemon=True).start()

    def next_ref(self, idx: int):
        import time

        while True:
            with self.lock:
                if self.queues[idx]:
                    ref = self.queues[idx].popleft()
                    self._handed.append(ref)
                    return ref
                if self.done:
                    return None
            time.sleep(0.01)


_SplitCoordinator = ray_tpu.remote(_SplitCoordinator)


# ----------------------------------------------------------- constructors
def _read(tasks: list) -> Dataset:
    return Dataset(L.ExecutionPlan([L.Read(tasks)]))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return _read(ds.range_tasks(n, parallelism))


def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    return _read(ds.items_tasks(list(items), parallelism))


def from_numpy(arr, column: str = "data") -> Dataset:
    arrs = arr if isinstance(arr, list) else [arr]
    return _read(ds.numpy_tasks(arrs, column))


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa

    dfs = dfs if isinstance(dfs, list) else [dfs]
    tables = [pa.Table.from_pandas(d, preserve_index=False) for d in dfs]

    def mk(t):
        def read():
            yield t

        return read

    return _read([mk(t) for t in tables])


def from_arrow(tables) -> Dataset:
    tables = tables if isinstance(tables, list) else [tables]

    def mk(t):
        def read():
            yield t

        return read

    return _read([mk(t) for t in tables])


def read_parquet(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.parquet_tasks(paths, parallelism))


def read_csv(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.csv_tasks(paths, parallelism))


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.json_tasks(paths, parallelism))


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.text_tasks(paths, parallelism))


def read_images(paths, *, parallelism: int = 8,
                size: tuple | None = None,
                mode: str | None = None) -> Dataset:
    """Image files → {"image": [h,w,c] uint8, "path"} rows (ray:
    read_images / image_datasource.py)."""
    return _read(ds.image_tasks(paths, parallelism, size=size, mode=mode))


def read_binary_files(paths, *, parallelism: int = 8) -> Dataset:
    """Whole files → {"bytes", "path"} rows (ray: read_binary_files)."""
    return _read(ds.binary_tasks(paths, parallelism))


def read_tfrecords(paths, *, parallelism: int = 8,
                   verify: bool = False) -> Dataset:
    """TFRecord files → {"record": bytes} rows (ray: read_tfrecords /
    tfrecords_datasource.py).  verify=True additionally checks payload
    CRCs (slower: pure-python crc32c)."""
    return _read(ds.tfrecord_tasks(paths, parallelism, verify=verify))


def from_generators(fns: list) -> Dataset:
    return _read(ds.generator_tasks(fns))


def read_sql(sql: str, connection_factory, *, parallelism: int = 1
             ) -> Dataset:
    """DB-API query → Dataset (ray: read_sql; sqlite3 works out of the
    box, any DB-API connection factory is accepted)."""
    return _read(ds.sql_tasks(sql, connection_factory, parallelism))


def read_avro(paths, *, parallelism: int = 8) -> Dataset:
    """Avro object-container files → one row per record (ray:
    read_avro; pure-python codec — see datasource.avro_tasks)."""
    return _read(ds.avro_tasks(paths, parallelism))


def read_webdataset(paths, *, parallelism: int = 8) -> Dataset:
    """WebDataset tar shards → one row per sample with a bytes column
    per extension (ray: read_webdataset)."""
    return _read(ds.webdataset_tasks(paths, parallelism))


def from_huggingface(dataset, *, parallelism: int = 8) -> Dataset:
    """A `datasets.Dataset` (local/in-memory) → Dataset via its arrow
    table (ray: from_huggingface)."""
    return _read(ds.huggingface_tasks(dataset, parallelism))
