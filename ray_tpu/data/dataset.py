"""Dataset: the lazy, streaming public API.

Analog of ray: python/ray/data/dataset.py:139 (Dataset), with the same
contract: transformations are lazy logical ops; consumption plans and
streams through the executor (SURVEY §3.6); blocks live in the object
store, not the driver.

TPU-native: `streaming_split` feeds per-train-worker shards through a
coordinator actor; `iter_jax_batches` double-buffers into HBM.
"""
from __future__ import annotations

import builtins
import itertools
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data import datasource as ds
from ray_tpu.data import logical as L
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.executor import StreamingExecutor
from ray_tpu.data.iterator import DataIterator


class Dataset:
    def __init__(self, plan: L.ExecutionPlan):
        self._plan = plan
        self._materialized: list | None = None   # block refs once computed
        self._union_sources: list | None = None

    def _as_plan(self) -> L.ExecutionPlan:
        """A logical plan view even for materialized/union datasets, so
        every transformation composes (post-union maps, etc.)."""
        if self._plan is not None:
            return self._plan
        self.materialize()
        refs = self._materialized

        def mk(ref):
            def read() -> Iterator:
                yield ray_tpu.get(ref)

            return read

        return L.ExecutionPlan([L.Read([mk(r) for r in refs])])

    # ------------------------------------------------------ transformations
    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._as_plan().with_op(op))

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with(L.MapRows(fn))

    def map_batches(self, fn, *, batch_size: int | None = None,
                    batch_format: str = "numpy", compute: str | None = None,
                    concurrency: int | tuple | None = None,
                    fn_args: tuple = (), fn_kwargs: dict | None = None,
                    fn_constructor_args: tuple = (),
                    num_cpus: float | None = None,
                    num_tpus: float = 0.0) -> "Dataset":
        """fn: batch->batch (callable) or a class (stateful actor UDF,
        compute="actors" or an ActorPoolStrategy)."""
        from ray_tpu.data.interfaces import ActorPoolStrategy

        if isinstance(compute, ActorPoolStrategy):
            if concurrency is None:
                concurrency = (compute.min_size, compute.max_size)
            compute = "actors"
        if compute is None:
            compute = "actors" if isinstance(fn, type) else "tasks"
        return self._with(L.MapBatches(
            fn, batch_size=batch_size, batch_format=batch_format,
            compute=compute, concurrency=concurrency, fn_args=fn_args,
            fn_kwargs=fn_kwargs or {},
            fn_constructor_args=fn_constructor_args,
            num_cpus=num_cpus, num_tpus=num_tpus))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with(L.Filter(fn))

    def flat_map(self, fn: Callable[[dict], Iterable[dict]]) -> "Dataset":
        return self._with(L.FlatMap(fn))

    def add_column(self, name: str, fn: Callable[[dict], Any]) -> "Dataset":
        def add(row):
            row = dict(row)
            row[name] = fn(row)
            return row

        return self.map(add)

    def drop_columns(self, cols: list[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}

        return self.map_batches(drop)

    def select_columns(self, cols: list[str]) -> "Dataset":
        def select(batch):
            return {k: batch[k] for k in cols}

        return self.map_batches(select)

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(L.Repartition(num_blocks))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._with(L.RandomShuffle(seed))

    def randomize_block_order(self, *, seed: int | None = None) -> "Dataset":
        """Shuffle BLOCKS, not rows — the cheap decorrelator (ray:
        Dataset.randomize_block_order)."""
        import random as _random

        self.materialize()
        blocks = list(self._materialized)
        _random.Random(seed).shuffle(blocks)
        return _from_blocks(blocks)

    def random_sample(self, fraction: float,
                      *, seed: int | None = None) -> "Dataset":
        """Row-level Bernoulli sample (ray: Dataset.random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        def sample(batch):
            import numpy as _np

            n = len(next(iter(batch.values()), []))
            if seed is None:
                rng = _np.random.default_rng()
            else:
                # Distinct deterministic stream PER BATCH: seeding every
                # batch with the bare user seed drew the identical
                # keep-mask in every block — correlated, not i.i.d.
                # (round-4 advisor finding).  No batch index reaches the
                # UDF, so fold a content digest into the seed sequence:
                # schedule-independent, and distinct blocks get distinct
                # streams.
                import pickle
                import zlib

                h = 0
                for k in sorted(batch):
                    a = _np.asarray(batch[k])
                    if a.dtype.kind in "OUS":
                        # Object/str columns: tobytes() would hash
                        # PyObject POINTERS — different every process.
                        # Pickle of the prefix is stable content.
                        buf = pickle.dumps(list(a[:64]), protocol=4)
                    else:
                        buf = _np.ascontiguousarray(a).tobytes()[:4096]
                    h = zlib.crc32(buf, h)
                rng = _np.random.default_rng(
                    _np.random.SeedSequence([seed & 0xFFFFFFFF, h, n]))
            keep = rng.random(n) < fraction
            return {k: _np.asarray(v)[keep] for k, v in batch.items()}

        return self.map_batches(sample)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n))

    def groupby(self, key: str | list[str] | None):
        from ray_tpu.data.grouped import GroupedData

        keys = [key] if isinstance(key, str) else (key or [])
        return GroupedData(self, keys)

    def union(self, *others: "Dataset") -> "Dataset":
        plans = [self._as_plan(), *[o._as_plan() for o in others]]
        u = Dataset.__new__(Dataset)
        u._plan = None
        u._materialized = None
        u._union_sources = plans
        return u

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise join of equal-length datasets."""
        left = self.materialize()._materialized
        right = other.materialize()._materialized

        @ray_tpu.remote
        def zip_blocks(*parts):
            import pyarrow as pa

            half = len(parts) // 2
            lt = BlockAccessor.concat(list(parts[:half]))
            rt = BlockAccessor.concat(list(parts[half:]))
            cols = {**BlockAccessor(lt).to_numpy(),
                    **BlockAccessor(rt).to_numpy()}
            from ray_tpu.data.block import _to_table

            return _to_table(cols)

        ref = zip_blocks.remote(*left, *right)
        out = Dataset.__new__(Dataset)
        out._plan = None
        out._materialized = [ref]
        out._union_sources = None
        return out

    # ------------------------------------------------------------ execution
    def _ref_iter(self) -> Iterator:
        if self._materialized is not None:
            return iter(self._materialized)
        if getattr(self, "_union_sources", None):
            self._executors = []

            def chain():
                for p in self._union_sources:
                    ex = StreamingExecutor(p)
                    self._executors.append(ex)
                    yield from ex.execute()

            return chain()
        ex = StreamingExecutor(self._plan)
        self._executors = [ex]
        return ex.execute()

    def stats(self) -> str:
        """Per-operator execution stats of the most recent run (ray:
        Dataset.stats() backed by data/_internal/stats.py)."""
        exs = getattr(self, "_executors", None)
        if not exs:
            return "(dataset has not been executed yet)"
        return "\n".join(ex.stats() for ex in exs)

    def iterator(self) -> DataIterator:
        return DataIterator(self._ref_iter)

    def materialize(self) -> "Dataset":
        if self._materialized is None:
            self._materialized = list(self._ref_iter())
        return self

    # ----------------------------------------------------------- consumption
    def iter_batches(self, **kw) -> Iterator:
        return self.iterator().iter_batches(**kw)

    def iter_rows(self) -> Iterator[dict]:
        return self.iterator().iter_rows()

    def iter_torch_batches(self, **kw) -> Iterator:
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw) -> Iterator:
        return self.iterator().iter_jax_batches(**kw)

    def iter_tf_batches(self, **kw) -> Iterator:
        """Gated on tensorflow being installed (not in this image); the
        numpy batches convert 1:1 (ray: Dataset.iter_tf_batches)."""
        try:
            import tensorflow as tf  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "iter_tf_batches requires tensorflow; use "
                "iter_jax_batches / iter_torch_batches") from e

        def gen():
            for batch in self.iter_batches(**kw):
                yield {k: tf.convert_to_tensor(v)
                       for k, v in batch.items()}

        return gen()

    def take(self, n: int = 20) -> list[dict]:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(BlockAccessor.for_block(ray_tpu.get(r)).num_rows()
                   for r in self._ref_iter())

    def schema(self):
        for ref in self._ref_iter():
            return BlockAccessor.for_block(ray_tpu.get(ref)).schema()
        return None

    def columns(self) -> list[str]:
        sch = self.schema()
        return list(sch.names) if sch is not None else []

    def num_blocks(self) -> int:
        self.materialize()
        return len(self._materialized)

    def size_bytes(self) -> int:
        return sum(BlockAccessor.for_block(ray_tpu.get(r)).size_bytes()
                   for r in self._ref_iter())

    def to_pandas(self):
        import pandas as pd

        frames = [BlockAccessor.for_block(ray_tpu.get(r)).to_pandas()
                  for r in self._ref_iter()]
        frames = [f for f in frames if not f.empty] or frames[:1]
        return pd.concat(frames, ignore_index=True) if frames \
            else pd.DataFrame()

    def to_numpy(self) -> dict[str, np.ndarray]:
        return self.iterator().materialize_numpy()

    def to_numpy_refs(self) -> list:
        """One ref per block, each a dict of column arrays (ray:
        Dataset.to_numpy_refs)."""
        @ray_tpu.remote
        def conv(block):
            return BlockAccessor.for_block(block).to_numpy()

        return [conv.remote(r) for r in self._ref_iter()]

    def to_arrow_refs(self) -> list:
        """Block refs as Arrow tables — blocks ARE Arrow tables here, so
        this is the materialized ref list (ray: Dataset.to_arrow_refs)."""
        return list(self._ref_iter())

    def names(self) -> list[str]:
        return self.columns()

    def types(self) -> list:
        sch = self.schema()
        return list(sch.types) if sch is not None else []

    def copy(self) -> "Dataset":
        """New handle over the same lazy plan / materialized blocks
        (execution state like stats is NOT shared)."""
        out = Dataset.__new__(Dataset)
        out._plan = self._plan
        out._materialized = (list(self._materialized)
                             if self._materialized is not None else None)
        out._union_sources = getattr(self, "_union_sources", None)
        return out

    def context(self):
        from ray_tpu.data.context import DataContext

        return DataContext.get_current()

    def input_files(self) -> list[str]:
        """Source paths recorded by file-based read ops, when any (ray:
        Dataset.input_files)."""
        files: list[str] = []
        for plan in ([self._plan] if self._plan is not None
                     else (getattr(self, "_union_sources", None) or [])):
            for op in plan.ops:
                files.extend(getattr(op, "input_files", None) or ())
        return files

    # ------------------------------------------------------- aggregations
    def _column(self, on: str) -> np.ndarray:
        parts = [BlockAccessor.for_block(ray_tpu.get(r)).to_numpy()[on]
                 for r in self._ref_iter()]
        parts = [p for p in parts if len(p)]
        if not parts:
            return np.array([])
        return np.concatenate(parts)

    def sum(self, on: str):
        v = self._column(on)
        return v.sum().item() if len(v) else None

    def min(self, on: str):
        v = self._column(on)
        return v.min().item() if len(v) else None

    def max(self, on: str):
        v = self._column(on)
        return v.max().item() if len(v) else None

    def mean(self, on: str):
        v = self._column(on)
        return v.mean().item() if len(v) else None

    def std(self, on: str, ddof: int = 1):
        v = self._column(on)
        return v.std(ddof=ddof).item() if len(v) > ddof else None

    def aggregate(self, **aggs: tuple[str, str]) -> dict:
        """Whole-dataset aggregation: aggregate(total=("v", "sum"),
        lo=("v", "min")) — the global counterpart of
        GroupedData.aggregate (ray: Dataset.aggregate with AggregateFn)."""
        out = {}
        for name, (col, kind) in aggs.items():
            if kind not in ("sum", "min", "max", "mean", "std", "count"):
                raise ValueError(f"unknown aggregation {kind!r}")
            if kind == "count":
                out[name] = self.count()
            else:
                out[name] = getattr(self, kind)(col)
        return out

    def unique(self, column: str) -> list:
        """Distinct values of one column (ray: Dataset.unique)."""
        v = self._column(column)
        return sorted(np.unique(v).tolist()) if len(v) else []

    def take_batch(self, batch_size: int = 20) -> dict[str, np.ndarray]:
        """First batch as a dict of column arrays (ray:
        Dataset.take_batch)."""
        for batch in self.limit(batch_size).iter_batches(
                batch_size=batch_size):
            return batch
        return {}

    # ---------------------------------------------------------------- split
    def split(self, n: int) -> list["Dataset"]:
        """Materialize and split into n datasets by block round-robin."""
        self.materialize()
        outs = []
        for i in builtins.range(n):
            part = self._materialized[i::n]
            d = Dataset.__new__(Dataset)
            d._plan = None
            d._materialized = part
            d._union_sources = None
            outs.append(d)
        return outs

    def split_at_indices(self, indices: list[int]) -> list["Dataset"]:
        """Split by ROW indices (ray: Dataset.split_at_indices).  Splits
        at BLOCK boundaries: interior blocks move whole (by ref); only
        the blocks straddling a cut are re-sliced in tasks.  The driver
        touches per-block row counts, never rows — no O(dataset)
        materialization (round-4 advisor finding)."""
        from ray_tpu.data.block import _rows_to_table

        self.materialize()
        refs = list(self._materialized)

        @ray_tpu.remote
        def _nrows(block):
            return BlockAccessor.for_block(block).num_rows()

        @ray_tpu.remote
        def _cut(block, start, stop):
            return BlockAccessor.for_block(block).slice(start, stop)

        counts = ray_tpu.get([_nrows.remote(r) for r in refs])
        total = builtins.sum(counts)
        pieces = []
        prev = 0
        for ix in [*indices, total]:
            ix = min(max(ix, prev), total)
            piece_refs = []
            off = 0
            for r, c in zip(refs, counts):
                lo, hi = off, off + c
                off = hi
                if c == 0 or hi <= prev or lo >= ix:
                    continue
                s, e = max(prev, lo) - lo, min(ix, hi) - lo
                piece_refs.append(r if (s == 0 and e == c)
                                  else _cut.remote(r, s, e))
            prev = ix
            if not piece_refs:
                piece_refs = [ray_tpu.put(_rows_to_table([]))]
            pieces.append(_from_blocks(piece_refs))
        return pieces

    def split_proportionately(self,
                              proportions: list[float]) -> list["Dataset"]:
        """ray: Dataset.split_proportionately — the last piece takes the
        remainder."""
        if not proportions or any(p <= 0 for p in proportions) \
                or builtins.sum(proportions) >= 1.0:
            raise ValueError("proportions must be positive and sum to <1")
        total = self.count()
        cuts, acc = [], 0
        for p in proportions:
            acc += int(total * p)
            cuts.append(acc)
        return self.split_at_indices(cuts)

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: int | None = None
                         ) -> tuple["Dataset", "Dataset"]:
        """ray: Dataset.train_test_split."""
        if not 0 < test_size < 1:
            raise ValueError("test_size must be in (0, 1)")
        base = self.random_shuffle(seed=seed) if shuffle else self
        train, test = base.split_proportionately([1.0 - test_size])
        return train, test

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> list[DataIterator]:
        """n DataIterators fed round-robin while execution streams
        (ray: Dataset.streaming_split dataset.py:1236 via a coordinator
        actor).  Each split may be consumed from a different process."""
        if self._materialized is not None:
            ops, mat = None, self._materialized
        else:
            ops, mat = self._as_plan().ops, None
        coord = _SplitCoordinator.options(num_cpus=0).remote(ops, mat, n)

        def make_factory(idx: int):
            def refs() -> Iterator:
                while True:
                    ref = ray_tpu.get(coord.next_ref.remote(idx))
                    if ref is None:
                        return
                    yield ref

            return refs

        its = [DataIterator(make_factory(i)) for i in builtins.range(n)]
        for it in its:
            it._coordinator = coord    # keep the actor alive
        return its

    # ---------------------------------------------------------------- write
    def _write(self, path: str, fmt: str) -> None:
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block, idx):
            return ds.write_block(block, path, fmt, idx)

        ray_tpu.get([write_one.remote(r, i) for i, r in enumerate(refs)])

    def write_parquet(self, path: str) -> None:
        self._write(path, "parquet")

    def write_csv(self, path: str) -> None:
        self._write(path, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, "json")

    def write_tfrecords(self, path: str) -> None:
        self._write(path, "tfrecord")

    def write_numpy(self, path: str, *, column: str | None = None) -> None:
        """One .npy per block (ray: Dataset.write_numpy)."""
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block, idx):
            import os as _os

            import numpy as _np

            _os.makedirs(path, exist_ok=True)
            cols = BlockAccessor.for_block(block).to_numpy()
            arr = cols[column] if column else \
                _np.stack([cols[k] for k in sorted(cols)], axis=-1)
            out = _os.path.join(path, f"part-{idx:05d}.npy")
            _np.save(out, arr)
            return out

        ray_tpu.get([write_one.remote(r, i) for i, r in enumerate(refs)])

    def write_sql(self, sql: str, connection_factory) -> None:
        """executemany per block through a DB-API factory (ray:
        Dataset.write_sql — e.g. "INSERT INTO t VALUES(?, ?)")."""
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block):
            rows = list(BlockAccessor.for_block(block).iter_rows())
            conn = connection_factory()

            def _py(v):
                # DB-API drivers bind numpy scalars as raw blobs.
                return v.item() if hasattr(v, "item") else v
            try:
                conn.cursor().executemany(
                    sql, [tuple(_py(v) for v in r.values()) for r in rows])
                conn.commit()
            finally:
                conn.close()
            return len(rows)

        # Serialized: DB-API modules (sqlite3) need one writer at a time
        # unless the user's factory handles locking.
        for r in refs:
            ray_tpu.get(write_one.remote(r))

    def write_webdataset(self, path: str) -> None:
        """One .tar shard per block; each row becomes files
        "<key>.<column>" (the read_webdataset inverse)."""
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block, idx):
            import io as _io
            import os as _os
            import tarfile as _tarfile

            _os.makedirs(path, exist_ok=True)
            out = _os.path.join(path, f"shard-{idx:05d}.tar")
            rows = list(BlockAccessor.for_block(block).iter_rows())
            with _tarfile.open(out, "w") as tf:
                for i, row in enumerate(rows):
                    key = str(row.get("__key__", f"{idx:05d}{i:07d}"))
                    for col, val in row.items():
                        if col == "__key__":
                            continue
                        data = val if isinstance(val, bytes) \
                            else str(val).encode()
                        info = _tarfile.TarInfo(f"{key}.{col}")
                        info.size = len(data)
                        tf.addfile(info, _io.BytesIO(data))
            return out

        ray_tpu.get([write_one.remote(r, i) for i, r in enumerate(refs)])

    def write_datasink(self, datasink) -> None:
        """Custom sink: datasink.write(block) runs once per block in a
        task; on_write_complete gets every result on the driver (ray:
        Dataset.write_datasink)."""
        datasink.on_write_start()
        refs = list(self._ref_iter())

        @ray_tpu.remote
        def write_one(block):
            return datasink.write(block)

        results = ray_tpu.get([write_one.remote(r) for r in refs])
        datasink.on_write_complete(results)

    def __repr__(self):
        if self._materialized is not None:
            return f"MaterializedDataset({len(self._materialized)} blocks)"
        return f"Dataset({self._plan})"


class _SplitCoordinator:
    """Actor running the streaming executor, handing refs to n consumers
    round-robin (ray: StreamSplitDataIterator's coordinator)."""

    def __init__(self, ops, materialized, n: int):
        import collections
        import threading

        self.n = n
        self.queues = [collections.deque() for _ in builtins.range(n)]
        self.done = False
        self.lock = threading.Lock()
        # Pin handed-out refs: this actor owns the blocks, and a consumer
        # may fetch a ref after the local ObjectRef would otherwise be
        # GC'd (owner frees → ObjectLostError at the borrower).
        self._handed: list = []

        def run():
            try:
                if materialized is not None:
                    refs = iter(materialized)
                else:
                    refs = StreamingExecutor(
                        L.ExecutionPlan(ops)).execute()
                for i, ref in enumerate(refs):
                    with self.lock:
                        self.queues[i % n].append(ref)
            finally:
                self.done = True

        threading.Thread(target=run, daemon=True).start()

    def next_ref(self, idx: int):
        import time

        while True:
            with self.lock:
                if self.queues[idx]:
                    ref = self.queues[idx].popleft()
                    self._handed.append(ref)
                    return ref
                if self.done:
                    return None
            time.sleep(0.01)


_SplitCoordinator = ray_tpu.remote(_SplitCoordinator)


def _from_blocks(blocks: list) -> Dataset:
    d = Dataset.__new__(Dataset)
    d._plan = None
    d._materialized = list(blocks)
    d._union_sources = None
    return d


# ----------------------------------------------------------- constructors
def _read(tasks: list, input_files: list | None = None) -> Dataset:
    return Dataset(L.ExecutionPlan([L.Read(tasks, input_files)]))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    return _read(ds.range_tasks(n, parallelism))


def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    return _read(ds.items_tasks(list(items), parallelism))


def from_numpy(arr, column: str = "data") -> Dataset:
    arrs = arr if isinstance(arr, list) else [arr]
    return _read(ds.numpy_tasks(arrs, column))


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa

    dfs = dfs if isinstance(dfs, list) else [dfs]
    tables = [pa.Table.from_pandas(d, preserve_index=False) for d in dfs]

    def mk(t):
        def read():
            yield t

        return read

    return _read([mk(t) for t in tables])


def from_arrow(tables) -> Dataset:
    tables = tables if isinstance(tables, list) else [tables]

    def mk(t):
        def read():
            yield t

        return read

    return _read([mk(t) for t in tables])


def read_parquet(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.parquet_tasks(paths, parallelism),
                 ds._expand_paths(paths, ".parquet"))


def read_csv(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.csv_tasks(paths, parallelism),
                 ds._expand_paths(paths, ".csv"))


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.json_tasks(paths, parallelism))


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    return _read(ds.text_tasks(paths, parallelism))


def read_images(paths, *, parallelism: int = 8,
                size: tuple | None = None,
                mode: str | None = None) -> Dataset:
    """Image files → {"image": [h,w,c] uint8, "path"} rows (ray:
    read_images / image_datasource.py)."""
    return _read(ds.image_tasks(paths, parallelism, size=size, mode=mode))


def read_binary_files(paths, *, parallelism: int = 8) -> Dataset:
    """Whole files → {"bytes", "path"} rows (ray: read_binary_files)."""
    return _read(ds.binary_tasks(paths, parallelism))


def read_numpy(paths, *, parallelism: int = 8) -> Dataset:
    """.npy files → {"data": array} rows, one block per file (ray:
    read_numpy; the write_numpy inverse)."""
    from ray_tpu.data.block import _to_table as _to_block_table

    files = ds._expand_paths(paths, ".npy")

    def mk(path):
        def read():
            import numpy as _np

            yield _to_block_table({"data": _np.load(path)})

        return read

    return _read([mk(p) for p in files], files)


def read_parquet_bulk(paths, *, parallelism: int = 8) -> Dataset:
    """One read task per file with no upfront metadata pass — our
    read_parquet is already per-file and metadata-free, so this is the
    same plan (ray: read_parquet_bulk exists to skip its sibling's
    costly metadata fetch)."""
    return read_parquet(paths, parallelism=parallelism)


def read_datasource(datasource, *, parallelism: int = 8) -> Dataset:
    """Custom Datasource → Dataset (ray: read_datasource)."""
    tasks = datasource.get_read_tasks(parallelism)
    if not tasks:
        raise ValueError("datasource produced no read tasks")
    return _read(tasks)


def range_tensor(n: int, *, shape: tuple = (1,),
                 parallelism: int = 8) -> Dataset:
    """{"data": i * ones(shape)} rows (ray: range_tensor)."""
    def mapper(batch):
        ids = batch["id"]
        reps = np.ones((len(ids), *shape), dtype=np.int64)
        return {"data": reps * np.asarray(ids).reshape(
            (-1,) + (1,) * len(shape))}

    return range(n, parallelism=parallelism).map_batches(mapper)


def from_numpy_refs(refs, column: str = "data") -> Dataset:
    """Refs to numpy arrays → Dataset (ray: from_numpy_refs)."""
    refs = refs if isinstance(refs, list) else [refs]

    def mk(r):
        def read():
            from ray_tpu.data.block import _to_table as _tt

            yield _tt({column: ray_tpu.get(r)})

        return read

    return _read([mk(r) for r in refs])


def from_pandas_refs(refs) -> Dataset:
    """Refs to pandas DataFrames → Dataset (ray: from_pandas_refs)."""
    refs = refs if isinstance(refs, list) else [refs]

    def mk(r):
        def read():
            import pyarrow as pa

            yield pa.Table.from_pandas(ray_tpu.get(r),
                                       preserve_index=False)

        return read

    return _read([mk(r) for r in refs])


def from_arrow_refs(refs) -> Dataset:
    """Refs to Arrow tables → Dataset; tables ARE blocks here, so the
    refs are consumed as-is (ray: from_arrow_refs)."""
    refs = refs if isinstance(refs, list) else [refs]
    return _from_blocks(list(refs))


def set_progress_bars(enabled: bool) -> bool:
    """ray: set_progress_bars — recorded on DataContext (executor stats
    remain the observability surface; there is no rich progress UI)."""
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    prev = getattr(ctx, "enable_progress_bars", True)
    ctx.enable_progress_bars = enabled
    return prev


def read_tfrecords(paths, *, parallelism: int = 8,
                   verify: bool = False) -> Dataset:
    """TFRecord files → {"record": bytes} rows (ray: read_tfrecords /
    tfrecords_datasource.py).  verify=True additionally checks payload
    CRCs (slower: pure-python crc32c)."""
    return _read(ds.tfrecord_tasks(paths, parallelism, verify=verify))


def from_generators(fns: list) -> Dataset:
    return _read(ds.generator_tasks(fns))


def read_sql(sql: str, connection_factory, *, parallelism: int = 1
             ) -> Dataset:
    """DB-API query → Dataset (ray: read_sql; sqlite3 works out of the
    box, any DB-API connection factory is accepted)."""
    return _read(ds.sql_tasks(sql, connection_factory, parallelism))


def read_avro(paths, *, parallelism: int = 8) -> Dataset:
    """Avro object-container files → one row per record (ray:
    read_avro; pure-python codec — see datasource.avro_tasks)."""
    return _read(ds.avro_tasks(paths, parallelism))


def read_webdataset(paths, *, parallelism: int = 8) -> Dataset:
    """WebDataset tar shards → one row per sample with a bytes column
    per extension (ray: read_webdataset)."""
    return _read(ds.webdataset_tasks(paths, parallelism))


def from_huggingface(dataset, *, parallelism: int = 8) -> Dataset:
    """A `datasets.Dataset` (local/in-memory) → Dataset via its arrow
    table (ray: from_huggingface)."""
    return _read(ds.huggingface_tasks(dataset, parallelism))
