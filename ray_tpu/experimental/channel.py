"""Mutable shared-memory channels (accelerated-DAG edges).

Analog of ray: src/ray/core_worker/experimental_mutable_object_manager.h
(+ python/ray/experimental/channel/): a FIXED shm buffer per DAG edge
that the producer rewrites in place every execution and the consumer
reads zero-copy — no per-call object naming, allocation, or RPC.  This
deliberately sits OUTSIDE the object-store arena: sealed arena objects
are immutable by invariant (CLAUDE.md); channels are their own tiny
/dev/shm segments (prefix `rtchan_`, disjoint from the arena sweep's
`raytpu_*` namespace) with an explicit writer/reader handshake.

Protocol (single writer, up to 64 registered readers, same host):

    header:  u64 write_seq | u64 payload_len | u64 n_readers
             | u64 claimed_mask | u64 acks[n_readers]

  - Each reader CLAIMS a slot (serialized by flock on the segment fd)
    on its first read; extra readers beyond n_readers fail loudly.
  - read(): wait write_seq > last_seen, copy payload, store
    acks[slot] = seq.  The per-slot store is a plain aligned u64 write
    owned by exactly one process — no read-modify-write races.
  - write(): wait until all n_readers slots are claimed AND every
    ack >= current seq (so nobody is still copying), then rewrite the
    payload in place, publish length, bump write_seq.

The waits are adaptive polls (brief check-spin → sched_yield → 50µs
sleeps; the reference uses named semaphores for the same role — the
yield phase gives the peer process the core on small hosts while
keeping reaction time in the tens of microseconds).
"""
from __future__ import annotations

import fcntl
import mmap
import os
import pickle
import struct
import time

_FIXED = struct.Struct("<QQQQ")    # write_seq, len, n_readers, claimed
_SHM_DIR = "/dev/shm"
MAX_READERS = 64


class _Waiter:
    """Adaptive wait: a few raw re-checks, then sched_yield (lets the
    peer run on shared cores with ~µs turnaround), then 50µs sleeps."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def pause(self) -> None:
        self.n += 1
        if self.n <= 8:
            return
        if self.n <= 512:
            os.sched_yield()
            return
        time.sleep(0.00005)


class ChannelError(RuntimeError):
    pass


class ChannelFull(ChannelError):
    pass


class ChannelClosed(ChannelError):
    pass


class Channel:
    """Single-writer, fixed-N-reader mutable shm channel.

        ch = Channel.create("edge0", max_size=1 << 20, n_readers=1)
        ch.write(value)                      # producer, repeatedly
        rd = Channel.open("edge0")
        value = rd.read(timeout=5.0)         # consumer, repeatedly

    Channels pickle by NAME (each process maps the same segment); a
    deserialized handle that reads becomes one of the n_readers — the
    reader SET is fixed, so ship exactly n_readers handles to readers.
    """

    def __init__(self, name: str, fd: int, mm: mmap.mmap, created: bool):
        self.name = name
        self._fd = fd
        self._mm = mm
        self._created = created
        self._last_read_seq = 0
        self._slot: int | None = None
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    @staticmethod
    def _fname(name: str) -> str:
        return f"rtchan_{name}"

    @classmethod
    def create(cls, name: str, max_size: int = 1 << 20,
               n_readers: int = 1) -> "Channel":
        if not 1 <= n_readers <= MAX_READERS:
            raise ChannelError(f"n_readers must be 1..{MAX_READERS}")
        path = os.path.join(_SHM_DIR, cls._fname(name))
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            # Stale segment from a crashed owner: the creator owns the
            # name, so supersede it (single-writer semantics).
            os.unlink(path)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        total = _FIXED.size + 8 * n_readers + max_size
        os.ftruncate(fd, total)
        mm = mmap.mmap(fd, total)
        _FIXED.pack_into(mm, 0, 0, 0, n_readers, 0)
        return cls(name, fd, mm, created=True)

    @classmethod
    def open(cls, name: str) -> "Channel":
        path = os.path.join(_SHM_DIR, cls._fname(name))
        fd = os.open(path, os.O_RDWR)
        mm = mmap.mmap(fd, os.fstat(fd).st_size)
        return cls(name, fd, mm, created=False)

    @classmethod
    def destroy(cls, name: str) -> None:
        """Unlink the segment (live handles keep their mapping)."""
        try:
            os.unlink(os.path.join(_SHM_DIR, cls._fname(name)))
        except OSError:
            pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
            os.close(self._fd)
        except (OSError, ValueError):
            pass
        if self._created:
            self.destroy(self.name)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - teardown
            pass

    # ------------------------------------------------------------- plumbing
    def _check_open(self) -> None:
        if self._closed:
            raise ChannelClosed(f"channel {self.name} is closed")

    def _hdr(self) -> tuple[int, int, int, int]:
        try:
            return _FIXED.unpack_from(self._mm, 0)
        except ValueError as e:
            raise ChannelClosed(f"channel {self.name}: {e}") from None

    def _ack(self, slot: int) -> int:
        return struct.unpack_from("<Q", self._mm,
                                  _FIXED.size + 8 * slot)[0]

    def _payload_off(self, n_readers: int) -> int:
        return _FIXED.size + 8 * n_readers

    @property
    def max_size(self) -> int:
        n = self._hdr()[2]
        return len(self._mm) - self._payload_off(n)

    def _claim_slot(self) -> int:
        """First read registers this handle as one of the n_readers
        (flock serializes claims across processes)."""
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        try:
            seq, length, n_readers, claimed = self._hdr()
            for i in range(n_readers):
                if not claimed & (1 << i):
                    struct.pack_into("<Q", self._mm, 24,
                                     claimed | (1 << i))
                    # A late claimer must not re-consume history: start
                    # acked-up-to the current seq minus one pending read.
                    struct.pack_into("<Q", self._mm,
                                     _FIXED.size + 8 * i,
                                     self._last_read_seq)
                    return i
            raise ChannelError(
                f"channel {self.name}: all {n_readers} reader slots "
                "claimed — the reader set is fixed at create()")
        finally:
            fcntl.flock(self._fd, fcntl.LOCK_UN)

    # ---------------------------------------------------------------- write
    def write(self, value, timeout: float | None = 10.0) -> None:
        """Serialize value into the channel in place.  Blocks until every
        registered reader acked the previous value (and until all
        n_readers have attached — the fixed-set handshake)."""
        self._check_open()
        payload = pickle.dumps(value, protocol=5)
        if len(payload) > self.max_size:
            raise ChannelFull(
                f"payload {len(payload)}B > channel max_size "
                f"{self.max_size}B")
        deadline = None if timeout is None else time.monotonic() + timeout
        full_mask = None
        waiter = _Waiter()
        while True:
            seq, _len, n_readers, claimed = self._hdr()
            if full_mask is None:
                full_mask = (1 << n_readers) - 1
            # The FIRST write may proceed before readers attach (nothing
            # can be mid-copy yet; late claimers start at ack 0 and read
            # it).  Every later write needs the full reader set attached
            # AND every ack caught up — nobody is still copying.
            acked = all(self._ack(i) >= seq for i in range(n_readers)
                        if claimed >> i & 1)
            if acked and (claimed == full_mask or seq == 0):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel {self.name}: waiting on readers "
                    f"(claimed={claimed:b}/{full_mask:b}, seq={seq})")
            waiter.pause()
        off = self._payload_off(n_readers)
        self._mm[off:off + len(payload)] = payload
        struct.pack_into("<Q", self._mm, 8, len(payload))   # length first
        struct.pack_into("<Q", self._mm, 0, seq + 1)        # then publish

    # ----------------------------------------------------------------- read
    def read(self, timeout: float | None = 10.0):
        """Blocking read of the NEXT value (each registered reader sees
        every value exactly once); acks so the writer may overwrite."""
        self._check_open()
        if self._slot is None:
            self._slot = self._claim_slot()
        deadline = None if timeout is None else time.monotonic() + timeout
        waiter = _Waiter()
        while True:
            seq, length, n_readers, _claimed = self._hdr()
            if seq > self._last_read_seq:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel {self.name}: no write past seq "
                    f"{self._last_read_seq}")
            waiter.pause()
        off = self._payload_off(n_readers)
        value = pickle.loads(bytes(self._mm[off:off + length]))
        self._last_read_seq = seq
        # Ack AFTER copying out (plain store to OUR slot — atomic, no
        # cross-reader read-modify-write): the writer may then rewrite.
        struct.pack_into("<Q", self._mm, _FIXED.size + 8 * self._slot,
                         seq)
        return value

    def __reduce__(self):
        return (Channel.open, (self.name,))
