"""Mutable shared-memory channels (accelerated-DAG edges).

Analog of ray: src/ray/core_worker/experimental_mutable_object_manager.h
(+ python/ray/experimental/channel/): a FIXED shm buffer per DAG edge
that the producer rewrites in place every execution and the consumer
reads zero-copy — no per-call object naming, allocation, or RPC.  This
deliberately sits OUTSIDE the object-store arena: sealed arena objects
are immutable by invariant (CLAUDE.md); channels are their own tiny
/dev/shm segments (prefix `rtchan_`, disjoint from the arena sweep's
`raytpu_*` namespace) with an explicit writer/reader handshake.

Protocol (single writer, up to 64 registered readers, same host):

    header:  u64 write_seq | u64 payload_len | u64 n_readers
             | u64 claimed_mask | u64 acks[n_readers]

  - Each reader CLAIMS a slot (serialized by flock on the segment fd)
    on its first read; extra readers beyond n_readers fail loudly.
  - read(): wait write_seq > last_seen, copy payload, store
    acks[slot] = seq.  The per-slot store is a plain aligned u64 write
    owned by exactly one process — no read-modify-write races.
  - write(): wait until all n_readers slots are claimed AND every
    ack >= current seq (so nobody is still copying), then rewrite the
    payload in place, publish length, bump write_seq.

Waits are a brief check-spin, then a BLOCKING sem_timedwait on a named
POSIX semaphore hint (the reference uses named semaphores for the same
role).  Blocking matters: N poll-spinning processes on a small host
starve the peer that should produce the data (measured 6.9ms/iter on a
3-stage chain vs 0.75ms after the change, same contended box).
"""
from __future__ import annotations

import fcntl
import ctypes
import mmap
import os
import pickle
import struct
import time

_FIXED = struct.Struct("<QQQQ")    # write_seq, len, n_readers, claimed
_SHM_DIR = "/dev/shm"
MAX_READERS = 64


class _Sem:
    """Named POSIX semaphore as a WAKEUP HINT (the reference's channels
    block on named semaphores for exactly this role).  Pure hint: every
    wait has a short timeout and the caller re-checks shared state, so a
    missed post only costs one timeout tick and a stale post one spin.
    Posts are bounded by `cap` (sem_getvalue) so stale hints can never
    accumulate past one spin-burst per wait.

    Polling (the old design) collapses on contended hosts: N processes
    sched_yield/sleep-spinning on one core starve the very process that
    should produce the data (measured 6.9ms/iter on a 3-stage DAG chain
    vs 0.36ms for the BLOCKING zmq path on the same box).  Blocking in
    sem_timedwait lets the kernel wake the one right waiter.
    """

    __slots__ = ("_sem", "_name")
    _libc = None
    _broken = False

    @classmethod
    def _lib(cls):
        if cls._libc is None and not cls._broken:
            try:
                lib = ctypes.CDLL(None, use_errno=True)
                lib.sem_open.restype = ctypes.c_void_p
                lib.sem_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                         ctypes.c_uint32, ctypes.c_uint32]
                lib.sem_post.argtypes = [ctypes.c_void_p]
                lib.sem_timedwait.argtypes = [ctypes.c_void_p,
                                              ctypes.c_void_p]
                lib.sem_getvalue.argtypes = [ctypes.c_void_p,
                                             ctypes.POINTER(ctypes.c_int)]
                lib.sem_close.argtypes = [ctypes.c_void_p]
                lib.sem_unlink.argtypes = [ctypes.c_char_p]
                cls._libc = lib
            except (OSError, AttributeError):
                cls._broken = True
        return cls._libc

    def __init__(self, name: str, create: bool):
        self._sem = None
        self._name = f"/rtsem_{name}".encode()
        lib = self._lib()
        if lib is None:
            return
        O_CREAT = 0o100
        if create:
            lib.sem_unlink(self._name)      # supersede stale (crash)
            sem = lib.sem_open(self._name, O_CREAT, 0o600, 0)
        else:
            # sem_open is variadic; the fixed 4-arg signature needs the
            # (ignored without O_CREAT) mode/value placeholders.
            sem = lib.sem_open(self._name, 0, 0, 0)
        self._sem = sem or None             # SEM_FAILED == NULL on glibc

    def post(self, cap: int) -> None:
        """Raise the value toward `cap` (never beyond: bounded hints)."""
        if self._sem is None:
            return
        lib = self._libc
        val = ctypes.c_int(0)
        while True:
            lib.sem_getvalue(self._sem, ctypes.byref(val))
            if val.value >= cap:
                return
            lib.sem_post(self._sem)
            if val.value + 1 >= cap:
                return

    def wait(self, timeout_s: float) -> None:
        """Block until a post or the timeout; caller re-checks state."""
        if self._sem is None:
            time.sleep(min(timeout_s, 0.00005))
            return
        deadline = time.clock_gettime(time.CLOCK_REALTIME) + timeout_s
        ts = struct.pack("qq", int(deadline),
                         int((deadline % 1.0) * 1e9))
        buf = ctypes.create_string_buffer(ts)
        self._libc.sem_timedwait(self._sem, buf)

    def close(self, unlink: bool = False) -> None:
        """The OWNING Channel decides unlink (its _created flag is the
        single source of truth — a duplicated flag here could diverge,
        e.g. tests that clear Channel._created to simulate crashes)."""
        lib = self._libc
        if self._sem is not None and lib is not None:
            lib.sem_close(self._sem)
            self._sem = None
        if unlink and lib is not None:
            lib.sem_unlink(self._name)

    @classmethod
    def unlink(cls, name: str) -> None:
        lib = cls._lib()
        if lib is not None:
            lib.sem_unlink(f"/rtsem_{name}".encode())


class ChannelError(RuntimeError):
    pass


class ChannelFull(ChannelError):
    pass


class ChannelClosed(ChannelError):
    pass


class Channel:
    """Single-writer, fixed-N-reader mutable shm channel.

        ch = Channel.create("edge0", max_size=1 << 20, n_readers=1)
        ch.write(value)                      # producer, repeatedly
        rd = Channel.open("edge0")
        value = rd.read(timeout=5.0)         # consumer, repeatedly

    Channels pickle by NAME (each process maps the same segment); a
    deserialized handle that reads becomes one of the n_readers — the
    reader SET is fixed, so ship exactly n_readers handles to readers.
    """

    def __init__(self, name: str, fd: int, mm: mmap.mmap, created: bool):
        self.name = name
        self._fd = fd
        self._mm = mm
        self._created = created
        self._last_read_seq = 0
        self._slot: int | None = None
        self._closed = False
        # Wakeup hints (see _Sem): data = writer -> readers, ack =
        # readers -> writer.  The seq/ack words in shm stay the truth.
        self._sem_data = _Sem(f"{name}_d", created)
        self._sem_ack = _Sem(f"{name}_a", created)

    # ------------------------------------------------------------ lifecycle
    @staticmethod
    def _fname(name: str) -> str:
        return f"rtchan_{name}"

    @classmethod
    def create(cls, name: str, max_size: int = 1 << 20,
               n_readers: int = 1) -> "Channel":
        if not 1 <= n_readers <= MAX_READERS:
            raise ChannelError(f"n_readers must be 1..{MAX_READERS}")
        path = os.path.join(_SHM_DIR, cls._fname(name))
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        except FileExistsError:
            # Stale segment from a crashed owner: the creator owns the
            # name, so supersede it (single-writer semantics).
            os.unlink(path)
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        total = _FIXED.size + 8 * n_readers + max_size
        os.ftruncate(fd, total)
        mm = mmap.mmap(fd, total)
        _FIXED.pack_into(mm, 0, 0, 0, n_readers, 0)
        return cls(name, fd, mm, created=True)

    @classmethod
    def open(cls, name: str) -> "Channel":
        path = os.path.join(_SHM_DIR, cls._fname(name))
        fd = os.open(path, os.O_RDWR)
        mm = mmap.mmap(fd, os.fstat(fd).st_size)
        return cls(name, fd, mm, created=False)

    @classmethod
    def destroy(cls, name: str) -> None:
        """Unlink the segment AND its wakeup semaphores (live handles
        keep their mappings).  Channel names are random per DAG compile,
        so anything destroy misses leaks in /dev/shm forever."""
        try:
            os.unlink(os.path.join(_SHM_DIR, cls._fname(name)))
        except OSError:
            pass
        _Sem.unlink(f"{name}_d")
        _Sem.unlink(f"{name}_a")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
            os.close(self._fd)
        except (OSError, ValueError):
            pass
        self._sem_data.close(unlink=self._created)
        self._sem_ack.close(unlink=self._created)
        if self._created:
            self.destroy(self.name)

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - teardown
            pass

    # ------------------------------------------------------------- plumbing
    def _check_open(self) -> None:
        if self._closed:
            raise ChannelClosed(f"channel {self.name} is closed")

    def _hdr(self) -> tuple[int, int, int, int]:
        try:
            return _FIXED.unpack_from(self._mm, 0)
        except ValueError as e:
            raise ChannelClosed(f"channel {self.name}: {e}") from None

    def _ack(self, slot: int) -> int:
        return struct.unpack_from("<Q", self._mm,
                                  _FIXED.size + 8 * slot)[0]

    def _payload_off(self, n_readers: int) -> int:
        return _FIXED.size + 8 * n_readers

    @property
    def max_size(self) -> int:
        n = self._hdr()[2]
        return len(self._mm) - self._payload_off(n)

    def _claim_slot(self) -> int:
        """First read registers this handle as one of the n_readers
        (flock serializes claims across processes)."""
        fcntl.flock(self._fd, fcntl.LOCK_EX)
        try:
            seq, length, n_readers, claimed = self._hdr()
            for i in range(n_readers):
                if not claimed & (1 << i):
                    struct.pack_into("<Q", self._mm, 24,
                                     claimed | (1 << i))
                    # A late claimer must not re-consume history: start
                    # acked-up-to the current seq minus one pending read.
                    struct.pack_into("<Q", self._mm,
                                     _FIXED.size + 8 * i,
                                     self._last_read_seq)
                    return i
            raise ChannelError(
                f"channel {self.name}: all {n_readers} reader slots "
                "claimed — the reader set is fixed at create()")
        finally:
            fcntl.flock(self._fd, fcntl.LOCK_UN)

    # ---------------------------------------------------------------- write
    def write(self, value, timeout: float | None = 10.0) -> None:
        """Serialize value into the channel in place.  Blocks until every
        registered reader acked the previous value (and until all
        n_readers have attached — the fixed-set handshake)."""
        self._check_open()
        payload = pickle.dumps(value, protocol=5)
        if len(payload) > self.max_size:
            raise ChannelFull(
                f"payload {len(payload)}B > channel max_size "
                f"{self.max_size}B")
        deadline = None if timeout is None else time.monotonic() + timeout
        full_mask = None
        spins = 0
        while True:
            seq, _len, n_readers, claimed = self._hdr()
            if full_mask is None:
                full_mask = (1 << n_readers) - 1
            # The FIRST write may proceed before readers attach (nothing
            # can be mid-copy yet; late claimers start at ack 0 and read
            # it).  Every later write needs the full reader set attached
            # AND every ack caught up — nobody is still copying.
            acked = all(self._ack(i) >= seq for i in range(n_readers)
                        if claimed >> i & 1)
            if acked and (claimed == full_mask or seq == 0):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel {self.name}: waiting on readers "
                    f"(claimed={claimed:b}/{full_mask:b}, seq={seq})")
            spins += 1
            if spins <= 8:
                continue
            self._sem_ack.wait(0.005 if deadline is None else
                               min(0.005, deadline - time.monotonic()))
        off = self._payload_off(n_readers)
        self._mm[off:off + len(payload)] = payload
        struct.pack_into("<Q", self._mm, 8, len(payload))   # length first
        struct.pack_into("<Q", self._mm, 0, seq + 1)        # then publish
        self._sem_data.post(n_readers)

    # ----------------------------------------------------------------- read
    def read(self, timeout: float | None = 10.0):
        """Blocking read of the NEXT value (each registered reader sees
        every value exactly once); acks so the writer may overwrite."""
        self._check_open()
        if self._slot is None:
            self._slot = self._claim_slot()
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, length, n_readers, _claimed = self._hdr()
            if seq > self._last_read_seq:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"channel {self.name}: no write past seq "
                    f"{self._last_read_seq}")
            spins += 1
            if spins <= 8:
                continue
            self._sem_data.wait(0.005 if deadline is None else
                                min(0.005, deadline - time.monotonic()))
        off = self._payload_off(n_readers)
        value = pickle.loads(bytes(self._mm[off:off + length]))
        self._last_read_seq = seq
        # Ack AFTER copying out (plain store to OUR slot — atomic, no
        # cross-reader read-modify-write): the writer may then rewrite.
        struct.pack_into("<Q", self._mm, _FIXED.size + 8 * self._slot,
                         seq)
        self._sem_ack.post(n_readers)
        return value

    def __reduce__(self):
        return (Channel.open, (self.name,))
