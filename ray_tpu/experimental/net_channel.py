"""DCN-backed mutable channels: the cross-node compiled-DAG edge.

Analog of ray: python/ray/experimental/channel/torch_tensor_nccl_channel.py
:191 (+ nccl_group.py:19) — the reference moves compiled-DAG tensors
between workers on different nodes over NCCL channels.  On TPU the
intra-slice tensor plane is ICI inside pjit programs, so the runtime's
cross-node edge rides DCN instead: one zmq ROUTER socket on the writer,
one DEALER per reader, same depth-1 protocol as the shm `Channel`
(write k+1 blocks until every reader acked k) so a DAG edge behaves
identically whichever transport the compiler picked.

Wire protocol (all frames on one DEALER<->ROUTER connection, ordered):
  reader -> writer:  [b"HELLO"]           claim a reader slot, once
                     [b"ACK", u64 seq]    value consumed, may overwrite
  writer -> reader:  [u64 seq, payload]   one value per iteration

The writer end is created IN the writer's process (`serve()` binds);
`handle()` returns a picklable reader handle carrying the endpoint, so
plans ship it to readers with no name-service round trip.  Reader
handles attach lazily on first read(), exactly like shm readers.
"""
from __future__ import annotations

import pickle
import struct
import threading
import time

import zmq

from ray_tpu.experimental.channel import (ChannelClosed, ChannelError,
                                          ChannelFull)

_SEQ = struct.Struct("<Q")


class NetChannelWriter:
    """Single-writer end: ROUTER bound on this process (writer side of a
    cross-node DAG edge).  NOT thread-safe (one DAG loop owns it), NOT
    picklable (readers get `handle()`)."""

    def __init__(self, name: str, host: str, max_size: int = 1 << 20,
                 n_readers: int = 1):
        self.name = name
        self.max_size = max_size
        self.n_readers = n_readers
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.ROUTER)
        self._sock.setsockopt(zmq.LINGER, 0)
        port = self._sock.bind_to_random_port(f"tcp://{host}")
        self.address = f"{host}:{port}"
        self._readers: list[bytes] = []       # claimed identities
        self._acks: dict[bytes, int] = {}
        self._seq = 0
        self._closed = False

    def handle(self) -> "NetChannelReader":
        """Picklable reader handle (ship one per reader, like the fixed
        reader set of the shm channel)."""
        return NetChannelReader(self.name, self.address)

    def _pump(self, deadline: float | None) -> None:
        """Absorb HELLO/ACK frames; one poll step."""
        timeout_ms = 50
        if deadline is not None:
            timeout_ms = max(0, min(50, int((deadline - time.monotonic())
                                            * 1000)))
        if not self._sock.poll(timeout_ms, zmq.POLLIN):
            return
        while True:
            try:
                frames = self._sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            if len(frames) < 2:
                continue
            ident, kind = frames[0], frames[1]
            if kind == b"HELLO":
                if ident not in self._acks:
                    if len(self._readers) >= self.n_readers:
                        # Fixed reader set — tell the extra reader off.
                        self._sock.send_multipart([ident, b"REJECT"])
                        continue
                    self._readers.append(ident)
                    self._acks[ident] = self._seq
            elif kind == b"ACK" and len(frames) >= 3:
                seq = _SEQ.unpack(frames[2])[0]
                if ident in self._acks and seq > self._acks[ident]:
                    self._acks[ident] = seq

    def write(self, value, timeout: float | None = 10.0) -> None:
        """Serialize and send to every reader; blocks until the full
        reader set attached AND everyone acked the previous value."""
        if self._closed:
            raise ChannelClosed(f"net channel {self.name} is closed")
        payload = pickle.dumps(value, protocol=5)
        if len(payload) > self.max_size:
            raise ChannelFull(
                f"payload {len(payload)}B > channel max_size "
                f"{self.max_size}B")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._pump(deadline)
            if (len(self._readers) == self.n_readers
                    and all(a >= self._seq for a in self._acks.values())):
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"net channel {self.name}: waiting on readers "
                    f"({len(self._readers)}/{self.n_readers} attached, "
                    f"acks={sorted(self._acks.values())}, seq={self._seq})")
        self._seq += 1
        seq_b = _SEQ.pack(self._seq)
        for ident in self._readers:
            self._sock.send_multipart([ident, seq_b, payload],
                                      copy=len(payload) < (1 << 16))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.close(0)
        except Exception:  # noqa: BLE001 - teardown
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


class NetChannelReader:
    """One reader end: DEALER connected to the writer's ROUTER.  Carries
    the endpoint in its pickle — deserializing ships the handle to the
    reader's process; the connection attaches on first read()."""

    def __init__(self, name: str, address: str):
        self.name = name
        self.address = address
        self._sock = None
        self._last_seq = 0
        self._closed = False

    def _attach(self):
        sock = zmq.Context.instance().socket(zmq.DEALER)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(f"tcp://{self.address}")
        sock.send_multipart([b"HELLO"])
        self._sock = sock
        return sock

    def read(self, timeout: float | None = 10.0):
        if self._closed:
            raise ChannelClosed(f"net channel {self.name} is closed")
        sock = self._sock or self._attach()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            timeout_ms = 100
            if deadline is not None:
                timeout_ms = max(0, min(100,
                                        int((deadline - time.monotonic())
                                            * 1000)))
            if sock.poll(timeout_ms, zmq.POLLIN):
                frames = sock.recv_multipart()
                if frames and frames[0] == b"REJECT":
                    raise ChannelError(
                        f"net channel {self.name}: all reader slots "
                        "claimed — the reader set is fixed at create")
                if len(frames) >= 2:
                    seq = _SEQ.unpack(frames[0])[0]
                    value = pickle.loads(frames[1])
                    self._last_seq = seq
                    sock.send_multipart([b"ACK", _SEQ.pack(seq)])
                    return value
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(
                    f"net channel {self.name}: no write past seq "
                    f"{self._last_seq}")

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.close(0)
            except Exception:  # noqa: BLE001
                pass

    def __reduce__(self):
        return (NetChannelReader, (self.name, self.address))


# ---------------------------------------------------------------- registry
# Writer ends live in the WRITER's process; the compiled-DAG plan refers
# to them by name.  `serve()` runs inside the writer actor (shipped via
# __ray_call__ at compile time) and parks the writer here for the DAG
# loop to pick up.
_served: dict[str, NetChannelWriter] = {}
_served_lock = threading.Lock()


def serve(name: str, max_size: int = 1 << 20,
          n_readers: int = 1) -> str:
    """Create (or return) the writer end in THIS process; returns its
    endpoint.  Runs on the writer actor at DAG-compile time."""
    from ray_tpu._private.worker import global_worker

    with _served_lock:
        w = _served.get(name)
        if w is None:
            host = global_worker().address.rsplit(":", 1)[0]
            w = NetChannelWriter(name, host, max_size=max_size,
                                 n_readers=n_readers)
            _served[name] = w
    return w.address


def serve_on_actor(_instance, name: str, max_size: int = 1 << 20,
                   n_readers: int = 1) -> str:
    """`__ray_call__`-shaped serve (the dispatch passes the actor
    instance first); used by the DAG compiler to bind writer ends."""
    return serve(name, max_size, n_readers)


def served_writer(name: str) -> NetChannelWriter | None:
    with _served_lock:
        return _served.get(name)


def unserve(name: str) -> None:
    with _served_lock:
        w = _served.pop(name, None)
    if w is not None:
        w.close()
