"""ray_tpu.experimental — accelerated-DAG building blocks.

Mutable shared-memory channels for repeated zero-allocation
producer→consumer handoff (reference: ray experimental channels,
src/ray/core_worker/experimental_mutable_object_manager.h).
"""
from ray_tpu.experimental.channel import Channel  # noqa: F401

__all__ = ["Channel"]
