"""ray_tpu.experimental — accelerated-DAG building blocks.

Mutable shared-memory channels for repeated zero-allocation
producer→consumer handoff (reference: ray experimental channels,
src/ray/core_worker/experimental_mutable_object_manager.h).
"""
from ray_tpu.experimental.channel import Channel  # noqa: F401


def object_sizes(refs) -> "list[int | None]":
    """Owner-table payload sizes for locally-owned refs, None when
    unknown (ray: ray.experimental reference-table introspection).
    Cheap — no payload fetch; Data's resource manager budgets with it.
    """
    from ray_tpu._private.worker import global_worker

    return global_worker().object_sizes(list(refs))


__all__ = ["Channel", "object_sizes"]
