"""Public core API: init/shutdown, remote, get/put/wait, actors, kill.

Analog of ray: python/ray/_private/worker.py public functions
(init:1227, get:2578, put:2693, wait:2758, remote:3171, get_actor:2904).
"""
from __future__ import annotations

import atexit
import json
import logging
import subprocess
import sys
import time
from typing import Any, Iterable, Sequence

from ray_tpu._private.config import Config
from ray_tpu._private.ids import JobID
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.object_ref import ObjectRef
from ray_tpu.remote_function import RemoteFunction

logger = logging.getLogger(__name__)

_head_processes: list[subprocess.Popen] = []
_initialized = False


def _read_json_line(proc: subprocess.Popen, timeout: float = 30.0) -> dict:
    """Read the child's one-line JSON address announcement from stdout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"head process exited with {proc.returncode}")
            time.sleep(0.01)
            continue
        line = line.strip()
        if line.startswith(b"{"):
            return json.loads(line)
    raise TimeoutError("head process did not announce its address")


def _spawn(args: list[str]) -> tuple[subprocess.Popen, dict]:
    proc = subprocess.Popen(
        [sys.executable, "-m", *args], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL if not __import__("os").environ.get(
            "RAY_TPU_HEAD_LOGS") else None)
    info = _read_json_line(proc)
    _head_processes.append(proc)
    return proc, info


def init(address: str | None = None,
         resources: dict[str, float] | None = None,
         namespace: str = "default",
         object_store_memory: int | None = None,
         _system_config: dict | None = None,
         log_to_driver: bool = True,
         logging_config: "LoggingConfig | None" = None) -> dict:
    """Start (or connect to) a cluster and attach this process as driver.

    Without `address`, boots a local head: controller + one node agent as
    subprocesses (ray: Node.start_head_processes node.py:1353 spawning
    gcs_server + raylet).  With `address` ("controller host:port"), attaches
    to a running cluster (ray: ray.init(address=...)).
    """
    global _initialized
    if _initialized:
        raise RuntimeError("ray_tpu.init() already called; "
                           "call ray_tpu.shutdown() first")
    import os as _os

    if logging_config is not None:
        # Driver logging now; spawned processes (controller, agents,
        # zygote-forked workers) pick the config up from the environment
        # at their own startup (ray: logging_config.py dictConfig).
        logging_config.apply()
        _os.environ.update(logging_config.env())

    if address is None:
        # Job-submission child drivers attach to the submitting cluster
        # (ray: RAY_ADDRESS honored by ray.init).
        address = _os.environ.get("RAY_TPU_ADDRESS") or None
    if address == "auto":
        address = _os.environ.get("RAY_TPU_ADDRESS") or None
        if address is None:
            raise ConnectionError(
                "address='auto' but no running cluster found "
                "(RAY_TPU_ADDRESS unset)")
    if address:
        # `ray://host:port`: if the endpoint is a client proxy
        # (ray_tpu.client.server), enter client mode — the API routes
        # through a per-client host driver and this process never joins
        # the cluster trust domain (ray: ray.init("ray://...") → client
        # server).  Otherwise (or with `ray-tpu://`) the scheme strips
        # and the driver attaches directly over DCN.
        is_ray_scheme = address.startswith("ray://")
        for scheme in ("ray-tpu://", "ray://"):
            if address.startswith(scheme):
                address = address[len(scheme):]
                break
        if is_ray_scheme:
            from ray_tpu import client as client_mod

            if client_mod.probe(address):
                client_mod.connect(address, namespace=namespace)
                _initialized = True
                atexit.register(shutdown)
                return {"controller_address": address,
                        "client_mode": True}
    config = Config().override(_system_config)
    if object_store_memory:
        config.object_store_memory = object_store_memory

    if address is None:
        # Workers must be able to unpickle functions defined in driver-side
        # modules (e.g. test files, scripts in odd directories): ship the
        # driver's sys.path so by-reference pickles resolve (the local-mode
        # slice of the reference's working_dir runtime env, ray:
        # python/ray/_private/runtime_env/working_dir.py).
        import os as _os

        _os.environ["RAY_TPU_DRIVER_SYS_PATH"] = json.dumps(
            [p for p in (q or _os.getcwd() for q in sys.path)
             if _os.path.exists(p)])
        _, cinfo = _spawn(["ray_tpu._private.controller",
                           "--config-json", config.to_json()])
        controller_addr = cinfo["controller_addr"]
        agent_args = ["ray_tpu._private.node_agent",
                      "--controller", controller_addr,
                      "--config-json", config.to_json()]
        if resources is not None:
            agent_args += ["--resources-json", json.dumps(resources)]
        _, ainfo = _spawn(agent_args)
        agent_addr = ainfo["agent_addr"]
        node_id = ainfo["node_id"]
    else:
        controller_addr = address
        agent_addr, node_id = _pick_agent(controller_addr)

    from ray_tpu._private.worker import CoreWorker, set_global_worker

    core = CoreWorker(mode="driver", controller_addr=controller_addr,
                      agent_addr=agent_addr, config=config,
                      node_id=node_id, job_id=JobID.from_random().hex(),
                      namespace=namespace)
    core.log_to_driver = log_to_driver
    core.start()
    # Learn the local node store's shm name so puts/gets mmap it directly
    # (plasma-client analog; workers get it via env from the agent).
    if not core.store_name:
        try:
            areply, _ = core.call(agent_addr, "ping", {}, timeout=10.0)
            core.store_name = areply.get("store_name", "")
        except Exception:  # noqa: BLE001 - agent RPC fallback still works
            pass
        if core.store_name:
            # Map + write-prefault off the hot path (see CoreWorker.start;
            # the driver only learns the store name here).
            import threading

            threading.Thread(target=core.warm_arena, daemon=True,
                             name="raytpu-arena-warm").start()
    # Fetch pub address + register the job.
    reply, _ = core.call(controller_addr, "ping", {}, timeout=30.0)
    if reply.get("pub_addr"):
        core.connect_events(reply["pub_addr"])
    core.call(controller_addr, "register_job",
              {"job_id": core.job_id, "driver_addr": core.address})
    set_global_worker(core)
    _initialized = True
    atexit.register(shutdown)
    return {"controller_address": controller_addr, "node_id": node_id}


def _pick_agent(controller_addr: str, timeout: float = 30.0) -> tuple[str, str]:
    """Attach to an existing cluster: wait for an alive node and use its agent."""
    import asyncio

    from ray_tpu._private.rpc import RpcClient

    async def _go():
        cli = RpcClient(address=controller_addr)
        deadline = time.monotonic() + timeout
        try:
            while time.monotonic() < deadline:
                reply, _ = await cli.call("list_nodes", {}, timeout=10.0)
                nodes = [n for n in reply["nodes"] if n["state"] == "ALIVE"]
                if nodes:
                    return nodes[0]["agent_addr"], nodes[0]["node_id"]
                await asyncio.sleep(0.2)
            raise TimeoutError("no alive nodes in cluster")
        finally:
            cli.close()

    return asyncio.run(_go())


def shutdown() -> None:
    global _initialized
    from ray_tpu import client as client_mod
    from ray_tpu._private import worker as worker_mod

    if client_mod._ctx is not None:
        client_mod._ctx.disconnect()
    if worker_mod._global_worker is not None:
        core = worker_mod._global_worker
        try:
            # Mark this job done so cluster harvests (the memory verb's
            # driver fan-out) stop probing a driver that exited cleanly.
            core.call(core.controller_addr, "job_finished",
                      {"job_id": core.job_id}, timeout=5.0)
        except Exception:  # noqa: BLE001
            pass
        try:
            core.shutdown()
        except Exception:  # noqa: BLE001
            pass
    for proc in _head_processes:
        if proc.poll() is None:
            proc.terminate()
    for proc in _head_processes:
        try:
            proc.wait(timeout=3.0)
        except subprocess.TimeoutExpired:
            proc.kill()
    _head_processes.clear()
    _initialized = False
    atexit.unregister(shutdown)


def is_initialized() -> bool:
    return _initialized


def method(*, concurrency_group: str | None = None,
           num_returns: int | str | None = None):
    """@ray_tpu.method: per-method options on an actor class (ray:
    @ray.method) — currently concurrency_group and num_returns."""
    def wrap(fn):
        opts = dict(getattr(fn, "__ray_tpu_method_opts__", {}))
        if concurrency_group is not None:
            opts["concurrency_group"] = concurrency_group
        if num_returns is not None:
            opts["num_returns"] = num_returns
        fn.__ray_tpu_method_opts__ = opts
        return fn

    return wrap


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes
    (ray: worker.py:3171)."""
    if len(args) == 1 and not kwargs and callable(args[0]):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    return decorator


def get(refs: ObjectRef | Sequence[ObjectRef],
        *, timeout: float | None = None) -> Any:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        return client_mod._ctx.get(refs, timeout)
    # Compiled-DAG execution results (ray: ray.get on CompiledDAGRef reads
    # the DAG's output channel, no object-store involvement).
    from ray_tpu.dag.dag_node import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        return refs.get(timeout)
    single = isinstance(refs, ObjectRef)
    ref_list = [refs] if single else list(refs)
    for r in ref_list:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get takes ObjectRefs, got {type(r)}")
    values = global_worker().get_objects(ref_list, timeout)
    return values[0] if single else values


def put(value: Any) -> ObjectRef:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        return client_mod._ctx.put(value)
    if isinstance(value, ObjectRef):
        raise TypeError("calling put() on an ObjectRef is not allowed")
    return global_worker().put_object(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: float | None = None,
         fetch_local: bool = True) -> tuple[list[ObjectRef], list[ObjectRef]]:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    if client_mod._ctx is not None:
        return client_mod._ctx.wait(refs, num_returns, timeout)
    return global_worker().wait(refs, num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        return client_mod._ctx.kill(actor)
    global_worker().kill_actor(actor.actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    from ray_tpu._private.worker import global_worker

    global_worker().cancel_task(ref)


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    from ray_tpu import client as client_mod
    from ray_tpu._private.worker import global_worker

    if client_mod._ctx is not None:
        return client_mod._ctx.get_actor(name, namespace)
    core = global_worker()
    reply, _ = core.call(
        core.controller_addr, "get_actor_by_name",
        {"name": name, "namespace": namespace or core.namespace},
        timeout=30.0)
    if not reply.get("found"):
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(reply["actor_id"])


def available_resources() -> dict[str, float]:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    reply, _ = core.call(core.controller_addr, "list_nodes", timeout=30.0)
    out: dict[str, float] = {}
    for n in reply["nodes"]:
        if n["state"] != "ALIVE":
            continue
        for k, v in n["available"].items():
            out[k] = out.get(k, 0.0) + v
    return out


def cluster_resources() -> dict[str, float]:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    reply, _ = core.call(core.controller_addr, "list_nodes", timeout=30.0)
    out: dict[str, float] = {}
    for n in reply["nodes"]:
        if n["state"] != "ALIVE":
            continue
        for k, v in n["resources"].items():
            out[k] = out.get(k, 0.0) + v
    return out


def nodes() -> list[dict]:
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    reply, _ = core.call(core.controller_addr, "list_nodes", timeout=30.0)
    return reply["nodes"]


def timeline() -> list[dict]:
    """Task state-transition events (ray: ray timeline → Chrome trace)."""
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    reply, _ = core.call(core.controller_addr, "get_task_events",
                         timeout=30.0)
    return reply["events"]


# --------------------------------------------------------------- compat
# Process-mode constants (ray: ray_constants SCRIPT_MODE/WORKER_MODE/
# LOCAL_MODE; same values for drop-in comparisons).
SCRIPT_MODE = 0
WORKER_MODE = 1
LOCAL_MODE = 2


class Language:
    """Frontend languages (ray: Language proto enum).  JAVA is an
    intentional gap (no JVM frontend — README); PYTHON and CPP map to
    the Python API and the native worker API (native/raytpu_api.h)."""
    PYTHON = "PYTHON"
    CPP = "CPP"


def get_gpu_ids() -> list:
    """Always empty: this framework schedules TPUs, not GPUs (ray:
    worker.py:992 get_gpu_ids).  Kept so reference-written code that
    probes GPU visibility degrades cleanly; see `get_tpu_ids`."""
    return []


def get_tpu_ids() -> list[int]:
    """IDs of TPU chips visible to this worker (the get_gpu_ids analog).

    Only the per-host singleton device worker holds the chip lease
    (PARITY: accelerator support); every other process sees none.
    """
    import os as _os

    if _os.environ.get("RAY_TPU_IS_DEVICE_WORKER") != "1":
        return []
    import jax

    return [d.id for d in jax.devices()]


def show_in_dashboard(message: str, key: str = "",
                      dtype: str = "text") -> None:
    """Attach a status message to this worker, rendered by the dashboard
    (ray: worker.py:2521).  Messages land in controller KV under the
    "dash" namespace keyed by worker+key, so multiple keys coexist and
    re-posting a key overwrites it."""
    if dtype not in ("text", "html"):
        raise ValueError(f"invalid dtype {dtype!r} (text|html)")
    import time as _time

    from ray_tpu._private.worker import global_worker
    from ray_tpu.runtime_context import get_runtime_context

    core = global_worker()
    ctx = get_runtime_context()
    payload = {"message": message, "dtype": dtype,
               "worker_id": ctx.get_worker_id(),
               "actor_id": ctx.get_actor_id(),
               "task_id": ctx.get_task_id(), "ts": _time.time()}
    core.call(core.controller_addr, "kv_put",
              {"ns": "dash", "key": f"{ctx.get_worker_id()}:{key}"},
              [json.dumps(payload).encode()], timeout=30.0)


def cpp_function(fn_name: str, lib_path: str):
    """Handle on a native function for cross-language invocation (ray:
    ray.cpp_function / cross_language.py).  `fn_name` must be registered
    with RAYTPU_REMOTE in the shared library at `lib_path`; `.remote()`
    ships bytes in and bytes out (the C ABI marshalling contract of
    native/raytpu_api.h — no cross-language object graph)."""
    from ray_tpu._private.cpp_runtime import cpp_task

    class _CppFunction:
        def __init__(self, task):
            self._task = task

        def options(self, **opts) -> "_CppFunction":
            return _CppFunction(self._task.options(**opts))

        def remote(self, payload: bytes = b"") -> ObjectRef:
            return self._task.remote(lib_path, fn_name, payload)

    return _CppFunction(cpp_task)


class ClientBuilder:
    """Builder-style client connection (ray: client_builder.py —
    `ray.client("ray://host:port").namespace("n").connect()`).  Thin
    veneer over `init`; `init("ray://...")` remains the primary path."""

    def __init__(self, address: str):
        self._address = address
        self._namespace = "default"

    def namespace(self, namespace: str) -> "ClientBuilder":
        self._namespace = namespace
        return self

    def connect(self) -> dict:
        return init(self._address, namespace=self._namespace)

    def disconnect(self) -> None:
        shutdown()
