"""Autoscaler v2: instance state machine, GCE TPU provider against a fake
API, and the chaos reconcile loop (kill a node -> replaced -> pending PG
schedules).  Reference analogs: ray autoscaler/v2/instance_manager tests +
_private/gcp provider tests (mocked API).
"""
import http.server
import json
import threading
import time

import pytest

from ray_tpu.autoscaler.v2 import (ALLOCATED, FAILED, QUEUED, RAY_RUNNING,
                                   REQUESTED, TERMINATED, InstanceManager)


class TestInstanceManager:
    def test_lifecycle_transitions(self):
        im = InstanceManager()
        inst = im.add({"resources": {"CPU": 1}})
        assert inst.state == QUEUED
        im.set_state(inst.instance_id, REQUESTED)
        im.set_state(inst.instance_id, ALLOCATED, provider_node_id="p1")
        im.set_state(inst.instance_id, RAY_RUNNING, cluster_node_id="c1")
        assert im.in_state(RAY_RUNNING)[0].provider_node_id == "p1"

    def test_illegal_transition_rejected(self):
        im = InstanceManager()
        inst = im.add({})
        with pytest.raises(ValueError, match="illegal transition"):
            im.set_state(inst.instance_id, RAY_RUNNING)  # QUEUED -> RUNNING

    def test_failed_is_terminal(self):
        im = InstanceManager()
        inst = im.add({})
        im.set_state(inst.instance_id, REQUESTED)
        im.set_state(inst.instance_id, FAILED, error="boom")
        with pytest.raises(ValueError):
            im.set_state(inst.instance_id, ALLOCATED)
        assert im.in_state(FAILED)[0].error == "boom"

    def test_json_roundtrip(self):
        im = InstanceManager()
        a = im.add({"resources": {"CPU": 2}})
        im.set_state(a.instance_id, REQUESTED)
        im2 = InstanceManager.from_json(im.to_json())
        assert im2.instances[a.instance_id].state == REQUESTED
        assert im2.instances[a.instance_id].node_config == {
            "resources": {"CPU": 2}}


class _FakeTPUAPI(http.server.BaseHTTPRequestHandler):
    """Minimal Cloud-TPU-v2 + metadata-server stand-in."""

    nodes: dict = {}      # class-level store: name -> node dict

    def log_message(self, *a):  # silence
        pass

    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.endswith("/token"):
            assert self.headers.get("Metadata-Flavor") == "Google"
            self._send(200, {"access_token": "fake-token",
                             "expires_in": 3600})
            return
        if self.path.endswith("/nodes"):
            self._send(200, {"nodes": list(self.nodes.values())})
            return
        name = self.path.rsplit("/", 1)[-1]
        if name in self.nodes:
            self._send(200, self.nodes[name])
        else:
            self._send(404, {"error": "not found"})

    def do_POST(self):
        assert self.headers.get("Authorization") == "Bearer fake-token"
        node_id = self.path.split("nodeId=")[-1]
        n = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(n)) if n else {}
        self.nodes[node_id] = {
            "name": f"projects/p/locations/z/nodes/{node_id}",
            "state": "READY",
            "networkEndpoints": [
                {"ipAddress": f"10.0.0.{len(self.nodes) + 1}"}],
            **body}
        self._send(200, {"name": f"operations/{node_id}"})

    def do_DELETE(self):
        name = self.path.rsplit("/", 1)[-1]
        if self.nodes.pop(name, None) is not None:
            self._send(200, {})
        else:
            self._send(404, {"error": "not found"})


@pytest.fixture
def fake_tpu_api():
    _FakeTPUAPI.nodes = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeTPUAPI)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestGCETPUProvider:
    def test_create_list_terminate_roundtrip(self, fake_tpu_api):
        from ray_tpu.autoscaler.gcp import GCETPUNodeProvider

        p = GCETPUNodeProvider(
            "proj", "us-central1-a", api_endpoint=fake_tpu_api,
            metadata_endpoint=fake_tpu_api, cluster_name="rt")
        ids = p.create_node({"accelerator_type": "v5litepod-8"}, 2)
        assert len(ids) == 2
        assert sorted(p.non_terminated_nodes()) == sorted(ids)
        assert p.is_running(ids[0])
        # Recorded request carries the slice shape + cluster label.
        rec = _FakeTPUAPI.nodes[ids[0]]
        assert rec["acceleratorType"] == "v5litepod-8"
        assert rec["labels"]["ray-cluster"] == "rt"
        p.terminate_node(ids[0])
        assert p.non_terminated_nodes() == [ids[1]]
        assert not p.is_running(ids[0])

    def test_foreign_nodes_ignored(self, fake_tpu_api):
        from ray_tpu.autoscaler.gcp import GCETPUNodeProvider

        _FakeTPUAPI.nodes["other"] = {
            "name": "projects/p/locations/z/nodes/other",
            "state": "READY", "labels": {"ray-cluster": "not-ours"}}
        p = GCETPUNodeProvider(
            "proj", "z", api_endpoint=fake_tpu_api,
            metadata_endpoint=fake_tpu_api, cluster_name="rt")
        assert p.non_terminated_nodes() == []


class TestReconcilerChaos:
    def test_kill_node_replaced_and_pg_schedules(self, ray_shared):
        """The VERDICT chaos scenario: a worker node dies; the reconciler
        detects it (cloud view AND cluster view), replaces it, and a
        pending placement group that needed that capacity schedules."""
        import ray_tpu
        from ray_tpu.autoscaler.node_provider import LocalNodeProvider
        from ray_tpu.autoscaler.v2 import Reconciler
        from ray_tpu.utils.placement_group import (placement_group,
                                                   remove_placement_group)
        from ray_tpu._private.worker import global_worker

        core = global_worker()
        provider = LocalNodeProvider(core.controller_addr)
        rec = Reconciler(provider, node_config={
            "resources": {"CPU": 1, "chaosx": 1}})
        try:
            rec.set_target(2)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                rec.reconcile_once()
                if len(rec.im.in_state(RAY_RUNNING)) == 2:
                    break
                time.sleep(0.5)
            assert len(rec.im.in_state(RAY_RUNNING)) == 2, rec.summary()

            # A PG needing BOTH special nodes becomes ready.
            pg = placement_group([{"chaosx": 1}, {"chaosx": 1}],
                                 strategy="SPREAD")
            assert pg.ready(timeout=30.0)
            remove_placement_group(pg)

            # Kill one node out from under the reconciler (SIGKILL the
            # agent process — the "cloud instance crashed" case).
            victim = rec.im.in_state(RAY_RUNNING)[0]
            provider.nodes[victim.provider_node_id]["proc"].kill()

            # New PG is pending until the reconciler replaces capacity.
            pg2 = placement_group([{"chaosx": 1}, {"chaosx": 1}],
                                  strategy="SPREAD")
            deadline = time.monotonic() + 90
            ready = False
            while time.monotonic() < deadline:
                rec.reconcile_once()
                if pg2.ready(timeout=1.0):
                    ready = True
                    break
                time.sleep(0.5)
            assert ready, (rec.summary(), "replacement never scheduled")
            assert rec.im.in_state(FAILED), "death was never recorded"
            remove_placement_group(pg2)
        finally:
            rec.set_target(0)
            for _ in range(5):
                rec.reconcile_once()
                time.sleep(0.2)
            for pid in list(provider.nodes):
                provider.terminate_node(pid)


class _RecordingProvider:
    def __init__(self):
        self.created = []

    def create_node(self, node_config, count=1):
        ids = [f"p{len(self.created) + i}" for i in range(count)]
        self.created.extend(ids)
        return ids

    def terminate_node(self, pid):
        pass

    def non_terminated_nodes(self):
        return list(self.created)


class TestReconcilerEdgeCases:
    def test_scale_down_cancels_queued_before_launch(self, ray_shared):
        from ray_tpu.autoscaler.v2 import Reconciler, TERMINATED

        provider = _RecordingProvider()
        rec = Reconciler(provider)
        rec.im = type(rec.im)()          # fresh table (ignore persisted)
        for _ in range(3):
            rec.im.add({})
        rec.set_target(0)
        rec.reconcile_once()
        assert len(rec.im.in_state(TERMINATED)) == 3
        assert provider.created == [], "cancelled instances were launched"

    def test_stuck_requested_fails_out(self, ray_shared):
        from ray_tpu.autoscaler.v2 import (FAILED, REQUESTED, Reconciler)

        provider = _RecordingProvider()
        rec = Reconciler(provider, launch_timeout_s=0.0)
        rec.im = type(rec.im)()
        inst = rec.im.add({})
        rec.im.set_state(inst.instance_id, REQUESTED)
        time.sleep(0.01)
        rec.set_target(0)
        rec.reconcile_once()
        assert rec.im.in_state(FAILED), rec.summary()
