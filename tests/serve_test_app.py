"""Importable app builders for declarative-config tests (the classes are
function-local so cloudpickle ships them by value to replicas)."""


def build_app(multiplier: int = 2):
    from ray_tpu import serve

    @serve.deployment
    class Mult:
        def __call__(self, x):
            return x * multiplier

    return Mult.bind()


def build_echo():
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4)
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    return Echo.bind()
