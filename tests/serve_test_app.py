"""Importable app builders for declarative-config tests (the classes are
function-local so cloudpickle ships them by value to replicas)."""


def build_app(multiplier: int = 2):
    from ray_tpu import serve

    @serve.deployment
    class Mult:
        def __call__(self, x):
            return x * multiplier

    return Mult.bind()


def build_echo():
    from ray_tpu import serve

    @serve.deployment(max_ongoing_requests=4)
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    return Echo.bind()


def build_llm():
    """Debug-scale LLM app for declarative engine_config tests."""
    from ray_tpu import serve

    return serve.deployment(serve.LLMServer).options(name="LLM").bind(
        "debug", max_batch=2, max_len=64, page_size=16)
