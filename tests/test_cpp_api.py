"""C++ language frontend: C ABI driver + native task execution.

Mirrors ray: cpp/include/ray/api.h (RAY_REMOTE / ray::Task / ray::Get)
and the C++ worker's task loop (cpp/src/ray/runtime/task/task_executor.cc).
A real C++ driver binary attaches to the cluster through the embedded
CPython bridge (native/capi.cc), submits tasks registered in a user
shared library, and workers execute them natively after dlopen.
"""
import os
import subprocess
import sysconfig

USER_TASKS_CC = r"""
#include "raytpu_api.h"

int Add(const uint8_t* in, uint64_t n, uint8_t** out, uint64_t* m) {
  raytpu::Reader r(in, n);
  int64_t a = r.Pod<int64_t>(), b = r.Pod<int64_t>();
  return raytpu::Writer().Pod<int64_t>(a + b).Out(out, m);
}
RAYTPU_REMOTE(Add)

int Upper(const uint8_t* in, uint64_t n, uint8_t** out, uint64_t* m) {
  raytpu::Reader r(in, n);
  std::string s = r.Str();
  for (auto& c : s) c = toupper(c);
  return raytpu::Writer().Str(s).Out(out, m);
}
RAYTPU_REMOTE(Upper)

extern "C" const char* raytpu_last_error(void);
int Boom(const uint8_t*, uint64_t, uint8_t**, uint64_t*) {
  return 7;  // nonzero = task error; surfaces as RuntimeError driver-side
}
RAYTPU_REMOTE(Boom)

struct Counter {
  int64_t v;
  static void* New(const uint8_t* in, uint64_t n) {
    raytpu::Reader r(in, n);
    return new Counter{r.Pod<int64_t>()};
  }
  int Incr(const uint8_t* in, uint64_t n, uint8_t** out, uint64_t* m) {
    raytpu::Reader r(in, n);
    v += r.Pod<int64_t>();
    return raytpu::Writer().Pod<int64_t>(v).Out(out, m);
  }
  int Value(const uint8_t*, uint64_t, uint8_t** out, uint64_t* m) {
    return raytpu::Writer().Pod<int64_t>(v).Out(out, m);
  }
};
RAYTPU_ACTOR(Counter)
RAYTPU_METHOD(Counter, Incr)
RAYTPU_METHOD(Counter, Value)
"""

DRIVER_CC = r"""
#include <cstdio>
#include "raytpu_api.h"

int main(int argc, char** argv) {
  const char* address = argv[1];
  const std::string lib = argv[2];
  raytpu::Init(address);

  // Object transport round-trip.
  auto ref = raytpu::Put("hello from c++");
  if (raytpu::Get(ref) != "hello from c++") return 2;

  // Native task execution in a worker.
  auto sum_ref = raytpu::Submit(
      lib, "Add", raytpu::Writer().Pod<int64_t>(3).Pod<int64_t>(4).Bytes());
  auto up_ref = raytpu::Submit(
      lib, "Upper", raytpu::Writer().Str("tpu").Bytes());
  auto mask = raytpu::Wait({sum_ref, up_ref}, 2, 120.0);
  if (mask[0] != 1 || mask[1] != 1) return 3;
  raytpu::Reader sum(raytpu::Get(sum_ref));
  if (sum.Pod<int64_t>() != 7) return 4;
  raytpu::Reader up(raytpu::Get(up_ref));
  if (up.Str() != "TPU") return 5;

  // Task errors propagate to Get.
  bool threw = false;
  try {
    raytpu::Get(raytpu::Submit(lib, "Boom", ""));
  } catch (const std::exception& e) {
    threw = true;
  }
  if (!threw) return 6;

  // C++ actor: stateful native instance hosted by a worker.
  auto counter = raytpu::CreateActor(
      lib, "Counter", raytpu::Writer().Pod<int64_t>(100).Bytes());
  raytpu::Call(counter, "Incr", raytpu::Writer().Pod<int64_t>(5).Bytes());
  auto v_ref = raytpu::Call(counter, "Incr",
                            raytpu::Writer().Pod<int64_t>(2).Bytes());
  raytpu::Reader v(raytpu::Get(v_ref));
  if (v.Pod<int64_t>() != 107) return 7;
  raytpu::Reader v2(raytpu::Get(raytpu::Call(counter, "Value", "")));
  if (v2.Pod<int64_t>() != 107) return 8;
  raytpu::KillActor(counter);

  printf("OK\n");
  raytpu::Shutdown();
  return 0;
}
"""


def test_cpp_function_from_python(ray_shared, tmp_path):
    """Cross-language call from a PYTHON driver into a native function
    (ray: ray.cpp_function — cross_language.py): bytes in, bytes out
    through the RAYTPU_REMOTE registry, no C++ driver involved."""
    import struct

    import ray_tpu
    from ray_tpu._private.cpp_runtime import CAPI_HEADER, capi_lib_path

    capi_so = capi_lib_path()
    build_dir = os.path.dirname(capi_so)
    native_dir = os.path.dirname(CAPI_HEADER)
    user_cc = tmp_path / "user_tasks.cc"
    user_cc.write_text(USER_TASKS_CC)
    user_so = tmp_path / "libuser_tasks.so"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", str(user_so),
         str(user_cc), f"-I{native_dir}", f"-L{build_dir}", "-lraytpu_capi",
         f"-Wl,-rpath,{build_dir}"],
        check=True, capture_output=True)

    add = ray_tpu.cpp_function("Add", str(user_so))
    out = ray_tpu.get(add.remote(struct.pack("<qq", 30, 12)), timeout=120)
    assert struct.unpack("<q", out)[0] == 42
    # .options passthrough keeps the task-option surface.
    out = ray_tpu.get(add.options(num_cpus=1).remote(
        struct.pack("<qq", -5, 5)), timeout=120)
    assert struct.unpack("<q", out)[0] == 0


def test_cpp_driver_end_to_end(ray_shared, tmp_path):
    import ray_tpu
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.cpp_runtime import CAPI_HEADER, capi_lib_path

    capi_so = capi_lib_path()
    build_dir = os.path.dirname(capi_so)
    native_dir = os.path.dirname(CAPI_HEADER)

    user_cc = tmp_path / "user_tasks.cc"
    user_cc.write_text(USER_TASKS_CC)
    user_so = tmp_path / "libuser_tasks.so"
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", str(user_so),
         str(user_cc), f"-I{native_dir}", f"-L{build_dir}", "-lraytpu_capi",
         f"-Wl,-rpath,{build_dir}"],
        check=True, capture_output=True)

    driver_cc = tmp_path / "driver.cc"
    driver_cc.write_text(DRIVER_CC)
    driver = tmp_path / "driver"
    libdir = sysconfig.get_config_var("LIBDIR")
    pyver = sysconfig.get_config_var("LDVERSION")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-o", str(driver), str(driver_cc),
         f"-I{native_dir}", f"-L{build_dir}", "-lraytpu_capi",
         f"-L{libdir}", f"-lpython{pyver}", "-ldl",
         f"-Wl,-rpath,{build_dir}", f"-Wl,-rpath,{libdir}"],
        check=True, capture_output=True)

    addr = worker_mod._global_worker.controller_addr
    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ray_tpu.__file__)))
    env = {**os.environ,
           "PYTHONPATH": os.pathsep.join(
               [repo_root, os.environ.get("PYTHONPATH", "")]
           ).rstrip(os.pathsep)}
    proc = subprocess.run([str(driver), addr, str(user_so)],
                          capture_output=True, text=True, timeout=240,
                          env=env)
    assert proc.returncode == 0, (proc.returncode, proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    assert "OK" in proc.stdout
