"""Wave-batched actor control plane (round 18).

Parity: the batched path (driver create_actors fusion → controller
scheduler wave → agent bulk create_actors) must behave byte-identically
to the legacy per-actor path — names, get_if_exists, resource refusals
(partial grants), PG-targeted actors — with RAY_TPU_ACTOR_WAVES=0
restoring the legacy chain for same-run A/B.

Event-driven scheduling: infeasible actors park on capacity signals
(never a blind backoff poll), PG-targeted actors park on the group's
CREATED/REMOVED transition, and DEAD actors are tombstone-GC'd so
10k-actor churn cannot grow the controller resident set.

Chaos: an agent SIGKILLed mid-wave (agent.create_actors=crash) must
reschedule every actor of the wave on survivors with zero leaked leases
and zero dead-process arena pins.
"""
import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.utils import state as rt_state


def _core():
    from ray_tpu._private.worker import global_worker

    return global_worker()


def _actor_states(namefilter=None):
    states = {}
    for a in rt_state.list_actors():
        if namefilter is None or (a.get("name") or "").startswith(namefilter):
            states[a["actor_id"]] = a["state"]
    return states


# ------------------------------------------------------------- parity
def test_wave_burst_parity(ray_shared):
    """A burst of unnamed actors through the batched path: every actor
    runs, state is isolated, ids are unique."""
    ray_tpu = ray_shared

    @ray_tpu.remote
    class Holder:
        def __init__(self, base):
            self.v = base

        def get(self):
            return self.v

    actors = [Holder.options(num_cpus=0.125).remote(i) for i in range(10)]
    assert len({a.actor_id for a in actors}) == 10
    vals = ray_tpu.get([a.get.remote() for a in actors], timeout=140.0)
    assert vals == list(range(10))
    for a in actors:
        ray_tpu.kill(a)


def test_wave_named_and_get_if_exists(ray_shared):
    """Named actors stay on the synchronous registration path: the
    name-taken error and get_if_exists dedup both still work under
    waves."""
    ray_tpu = ray_shared

    @ray_tpu.remote
    class Svc:
        def ping(self):
            return "pong"

    a = Svc.options(name="wave_svc", num_cpus=0.125).remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120.0) == "pong"
    with pytest.raises(ValueError):
        Svc.options(name="wave_svc", num_cpus=0.125).remote()
    b = Svc.options(name="wave_svc", num_cpus=0.125,
                    get_if_exists=True).remote()
    assert b.actor_id == a.actor_id
    ray_tpu.kill(a)


def test_wave_kill_switch_legacy_parity(ray_shared):
    """RAY_TPU_ACTOR_WAVES=0 (read per creation) restores the legacy
    per-actor chain — driver sync registration, controller per-actor
    scheduling — and bursts still come up correctly."""
    ray_tpu = ray_shared

    @ray_tpu.remote
    class Holder:
        def __init__(self, base):
            self.v = base

        def get(self):
            return self.v

    os.environ["RAY_TPU_ACTOR_WAVES"] = "0"
    try:
        actors = [Holder.options(num_cpus=0.125).remote(i)
                  for i in range(6)]
        vals = ray_tpu.get([a.get.remote() for a in actors], timeout=140.0)
        assert vals == list(range(6))
    finally:
        os.environ.pop("RAY_TPU_ACTOR_WAVES", None)
    for a in actors:
        ray_tpu.kill(a)


def test_immediate_kill_never_overtakes_registration(ray_shared):
    """kill() right after a batched create must not overtake the
    in-flight registration (remove-before-register would leak a live
    worker with a DEAD controller entry)."""
    ray_tpu = ray_shared

    @ray_tpu.remote
    class Quick:
        def ping(self):
            return 1

    actors = [Quick.options(num_cpus=0.125).remote() for _ in range(4)]
    for a in actors:
        ray_tpu.kill(a)
    ids = {a.actor_id for a in actors}
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        listed = {a["actor_id"]: a["state"] for a in rt_state.list_actors()
                  if a["actor_id"] in ids}
        if listed and all(s == "DEAD" for s in listed.values()):
            break
        time.sleep(0.5)
    # Every killed actor the controller still lists must be DEAD (some
    # may already be tombstone-GC'd, which is fine too).
    for aid, state in listed.items():
        assert state == "DEAD", (aid, state)


# ---------------------------------------------- partial grants / parking
def test_partial_grant_reschedules_refused_actors():
    """4 one-CPU actors against a 2-CPU node: one wave grants 2, parks
    2; killing the granted pair frees capacity and the parked pair is
    placed by the capacity signal (no blind poll)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(1)

        @ray_tpu.remote(num_cpus=1)
        class Unit:
            def ping(self):
                return os.getpid()

        actors = [Unit.remote() for _ in range(4)]
        refs = [a.ping.remote() for a in actors]
        ready, pending = ray_tpu.wait(refs, num_returns=2, timeout=120.0)
        assert len(ready) == 2
        # The two others are genuinely parked, not failed.
        time.sleep(0.5)
        states = set(_actor_states().values())
        assert "PENDING" in states and "ALIVE" in states, states
        placed = [a for a, r in zip(actors, refs) if r in ready]
        for a in placed:
            ray_tpu.kill(a)
        rest = [r for r in refs if r not in ready]
        assert len(ray_tpu.get(rest, timeout=120.0)) == 2
        for a, r in zip(actors, refs):
            if r not in ready:
                ray_tpu.kill(a)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_actor_on_pending_pg_parks_places_or_fails():
    """Actors targeting PENDING placement groups park on the group's
    transition (satellite fix: no sleep-spin, no driver-side block):
    a group that becomes feasible places its actor; a group that is
    REMOVED while pending fails its actor with a diagnostic cause."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.utils.placement_group import (placement_group,
                                                   remove_placement_group)

        cluster.wait_for_nodes(1)
        pg1 = placement_group([{"CPU": 3}])      # infeasible on 2 CPUs
        pg2 = placement_group([{"CPU": 99}])     # never feasible
        assert pg1.ready(timeout=3) is False

        @ray_tpu.remote(num_cpus=1)
        class InPg:
            def ping(self):
                return "placed"

        a1 = InPg.options(placement_group=pg1).remote()
        a2 = InPg.options(placement_group=pg2).remote()
        time.sleep(0.8)
        assert set(_actor_states().values()) == {"PENDING"}
        cluster.add_node(resources={"CPU": 4})
        assert pg1.ready(timeout=60), "PG never became ready after join"
        assert ray_tpu.get(a1.ping.remote(), timeout=120.0) == "placed"
        # Removing the still-PENDING group fails its parked actor.
        remove_placement_group(pg2)
        core = _core()
        deadline = time.monotonic() + 30
        state = cause = None
        while time.monotonic() < deadline:
            reply, _ = core.call(core.controller_addr, "get_actor_info",
                                 {"actor_id": a2.actor_id}, timeout=10.0)
            state, cause = reply.get("state"), reply.get("cause")
            if state == "DEAD":
                break
            time.sleep(0.5)
        assert state == "DEAD", state
        assert "placement group" in (cause or ""), cause
        ray_tpu.kill(a1)
        remove_placement_group(pg1)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# --------------------------------------------------- tombstones / nodes
def test_dead_actor_tombstone_gc():
    """DEAD actors keep death_cause visible for the grace window, then
    drop from the controller tables — churn cannot grow the resident
    set without bound."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster(config_json='{"actor_tombstone_grace_s": 1.0}')
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(1)

        @ray_tpu.remote(num_cpus=0.25)
        class Brief:
            def ping(self):
                return 1

        a = Brief.options(name="brief").remote()
        assert ray_tpu.get(a.ping.remote(), timeout=120.0) == 1
        aid = a.actor_id
        ray_tpu.kill(a)
        core = _core()
        # Within the grace window the tombstone (with cause) is visible.
        reply, _ = core.call(core.controller_addr, "get_actor_info",
                             {"actor_id": aid}, timeout=10.0)
        assert reply["state"] == "DEAD"
        # After the grace window the entry is GONE (UNKNOWN), and the
        # name table entry with it.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            reply, _ = core.call(core.controller_addr, "get_actor_info",
                                 {"actor_id": aid}, timeout=10.0)
            if reply["state"] == "UNKNOWN":
                break
            time.sleep(0.5)
        assert reply["state"] == "UNKNOWN", reply
        assert all(x["actor_id"] != aid for x in rt_state.list_actors())
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_unregister_node_membership_leave(ray_shared):
    """Graceful membership leave: the node disappears from the view
    (popped, not tombstoned) and its events fan out like a death."""
    core = _core()
    reply, _ = core.call(core.controller_addr, "register_node",
                         {"node_id": "ghost01",
                          "agent_addr": "127.0.0.1:1",
                          "resources": {"CPU": 0.0}}, timeout=10.0)
    assert "pub_addr" in reply
    assert any(n["node_id"] == "ghost01" for n in ray_tpu.nodes())
    reply, _ = core.call(core.controller_addr, "unregister_node",
                         {"node_id": "ghost01"}, timeout=10.0)
    assert reply["ok"]
    assert all(n["node_id"] != "ghost01" for n in ray_tpu.nodes())
    # Idempotent: a second leave is a clean no-op.
    reply, _ = core.call(core.controller_addr, "unregister_node",
                         {"node_id": "ghost01"}, timeout=10.0)
    assert not reply["ok"]


# ------------------------------------------------------------- chaos
@pytest.mark.chaos
def test_wave_error_failpoint_retries():
    """controller.actor_wave=nth:1+error: the first dispatch aborts
    before any agent RPC; the wave scheduler re-queues and the actor
    comes up on the next wave (one-shot site, counters prove it)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(1)
        core = _core()
        reply, _ = core.call(
            core.controller_addr, "failpoints",
            {"op": "set", "spec": "controller.actor_wave=nth:1+error"},
            timeout=10.0)
        assert reply["armed"]

        @ray_tpu.remote(num_cpus=0.25)
        class Sturdy:
            def ping(self):
                return "up"

        a = Sturdy.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=120.0) == "up"
        reply, _ = core.call(core.controller_addr, "failpoints",
                             {"op": "counters"}, timeout=10.0)
        assert reply["counters"]["controller.actor_wave"]["fired"] == 1
        ray_tpu.kill(a)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.chaos
def test_agent_crash_mid_wave_reschedules_on_survivors():
    """agent.create_actors=nth:1+crash on node 2: the agent SIGKILLs
    with a wave in flight.  Every actor of the dead node's sub-wave
    must reschedule on the survivor — zero leaked leases (survivor
    capacity returns to full after the kills), zero dead-process arena
    pins."""
    from test_chaos_adversarial import _arena_pins_settle

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)
        core = _core()
        reply, _ = core.call(
            n2["agent_addr"], "failpoints",
            {"op": "set", "spec": "agent.create_actors=nth:1+crash"},
            timeout=10.0)
        assert reply["armed"]

        @ray_tpu.remote(num_cpus=0.25)
        class Survivor:
            def where(self):
                return os.environ.get("RAY_TPU_NODE_ID", "")

        # 8 × 0.25 CPU: the hybrid policy spreads the wave over both
        # nodes once node 1 crosses the 0.5 utilization threshold, so
        # node 2's sub-wave is non-empty and dies with the agent.
        actors = [Survivor.remote() for _ in range(8)]
        homes = ray_tpu.get([a.where.remote() for a in actors],
                            timeout=140.0)
        assert len(homes) == 8
        # Everyone rescheduled onto the survivor.
        assert set(homes) == {n1["node_id"]}, set(homes)
        # The dead node is eventually observed dead.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
            if states.get(n2["node_id"]) != "ALIVE":
                break
            time.sleep(0.5)
        assert states.get(n2["node_id"]) != "ALIVE"
        for a in actors:
            ray_tpu.kill(a)
        # Zero leaked leases: node 1's full capacity comes back.
        deadline = time.monotonic() + 30
        avail = None
        while time.monotonic() < deadline:
            reply, _ = core.call(n1["agent_addr"], "ping", {},
                                 timeout=10.0)
            avail = reply["available"].get("CPU")
            if avail == 2.0 and not reply["active_leases"]:
                break
            time.sleep(0.5)
        assert avail == 2.0, f"leaked actor leases: CPU avail={avail}"
        stats = _arena_pins_settle()
        assert not stats.get("swept_dead_pins", 0), stats
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
