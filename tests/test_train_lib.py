"""Train library: JaxTrainer.fit end-to-end on the local runtime
(worker-group actors, report/checkpoint plumbing, failure restart).

Mirrors the reference's Train tests (ray: python/ray/train/tests/) which
run against a single-node ray.init with CPU backends.
"""
import os

import pytest

from ray_tpu.train import (Checkpoint, CheckpointConfig, FailureConfig,
                           JaxTrainer, RunConfig, ScalingConfig)


def _simple_loop(config):
    from ray_tpu import train

    ctx = train.get_context()
    for i in range(config.get("steps", 3)):
        train.report({"step": i, "loss": 1.0 / (i + 1),
                      "rank": ctx.get_world_rank(),
                      "world_size": ctx.get_world_size()})


class TestJaxTrainer:
    def test_fit_single_worker(self, ray_shared, tmp_path):
        trainer = JaxTrainer(
            _simple_loop,
            train_loop_config={"steps": 3},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="t1", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["step"] == 2
        assert result.metrics["world_size"] == 1
        assert len(result.metrics_history) == 3

    def test_fit_two_workers_lockstep(self, ray_shared, tmp_path):
        trainer = JaxTrainer(
            _simple_loop,
            train_loop_config={"steps": 2},
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(name="t2", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        # rank-0 metrics are the authoritative stream
        assert result.metrics["rank"] == 0
        assert result.metrics["world_size"] == 2

    def test_checkpoint_roundtrip(self, ray_shared, tmp_path):
        def loop(config):
            from ray_tpu import train

            ckpt = train.get_checkpoint()
            start = ckpt.to_dict()["step"] + 1 if ckpt else 0
            for i in range(start, start + 2):
                train.report({"step": i},
                             checkpoint=Checkpoint.from_dict({"step": i}))

        trainer = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="ck", storage_path=str(tmp_path)))
        r1 = trainer.fit()
        assert r1.metrics["step"] == 1
        assert r1.checkpoint is not None

        trainer2 = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="ck2", storage_path=str(tmp_path)),
            resume_from_checkpoint=r1.checkpoint)
        r2 = trainer2.fit()
        assert r2.metrics["step"] == 3   # resumed from step 1

    def test_num_to_keep(self, ray_shared, tmp_path):
        def loop(config):
            from ray_tpu import train

            for i in range(4):
                train.report({"step": i},
                             checkpoint=Checkpoint.from_dict({"step": i}))

        trainer = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="keep", storage_path=str(tmp_path),
                checkpoint_config=CheckpointConfig(num_to_keep=2)))
        r = trainer.fit()
        ckpt_dirs = [d for d in os.listdir(r.path)
                     if d.startswith("checkpoint_")]
        assert len(ckpt_dirs) == 2
        assert r.checkpoint.to_dict()["step"] == 3

    def test_train_fn_error_surfaces(self, ray_shared, tmp_path):
        def bad_loop(config):
            raise ValueError("boom at step 0")

        trainer = JaxTrainer(
            bad_loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="err", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is not None
        assert "boom at step 0" in str(result.error)

    def test_stop_criteria(self, ray_shared, tmp_path):
        def loop(config):
            from ray_tpu import train

            for i in range(100):
                train.report({"step": i})

        trainer = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="stop", storage_path=str(tmp_path),
                                 stop={"step": 5}))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["step"] < 100

    def test_jax_train_step_in_worker(self, ray_shared, tmp_path):
        """End-to-end slice: sharded llama train step inside a train worker
        (the §7-step-5 'one model' milestone, scaled to the test box)."""
        def loop(config):
            import jax

            from ray_tpu._private.config import ensure_cpu_devices

            ensure_cpu_devices(8)
            import jax.numpy as jnp

            from ray_tpu import train
            from ray_tpu.models import llama
            from ray_tpu.parallel.mesh import MeshConfig, create_mesh
            from ray_tpu.train import step as ts

            cfg = llama.LlamaConfig(
                vocab_size=128, dim=64, n_layers=1, n_heads=2, n_kv_heads=1,
                ffn_dim=128, max_seq=64, remat=False)
            # Reused workers may have initialized jax with 1 device already;
            # shard over whatever is available.
            n = len(jax.devices())
            mesh = create_mesh(MeshConfig(data=-1, fsdp=2 if n % 2 == 0 else 1),
                               devices=jax.devices())
            opt = ts.default_optimizer(total_steps=10)
            state = ts.sharded_init(jax.random.PRNGKey(0), cfg, opt, mesh)
            fn = ts.sharded_train_step(cfg, opt, mesh)
            tok = jnp.zeros((8, 32), jnp.int32)   # divisible by data×fsdp
            batch = {"inputs": tok, "targets": tok}
            with jax.set_mesh(mesh):
                for i in range(2):
                    state, m = fn(state, batch)
                    train.report({"loss": float(m["loss"]), "step": i})
            train.report(
                {"final": True},
                checkpoint=Checkpoint.from_pytree(
                    {"step": state.step}, use_orbax=False))

        trainer = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="e2e", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        assert result.checkpoint is not None
        restored = result.checkpoint.to_pytree()
        assert int(restored["step"]) == 2


class TestAsyncCheckpointWriter:
    """ISSUE-5 satellite: from_pytree_async offloads serialization+write
    to a background thread; wait()/register()/pickling are the explicit
    flush points."""

    def test_async_write_waits_and_round_trips(self, tmp_path):
        import numpy as np

        tree = {"w": np.arange(2048, dtype=np.float32), "step": 7}
        ckpt = Checkpoint.from_pytree_async(tree, use_orbax=False)
        assert ckpt.wait() is ckpt
        restored = ckpt.to_pytree()
        assert int(restored["step"]) == 7
        np.testing.assert_array_equal(restored["w"], tree["w"])

    def test_register_flushes_pending_write(self, tmp_path):
        import numpy as np

        from ray_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(str(tmp_path))
        ckpt = Checkpoint.from_pytree_async(
            {"w": np.ones(1 << 18, np.float32)}, use_orbax=False)
        stored = mgr.register(ckpt, {"loss": 1.0})
        # register() waited: the copied directory is complete.
        restored = stored.to_pytree()
        assert float(restored["w"][0]) == 1.0

    def test_pickle_is_a_flush_point(self, tmp_path):
        import pickle

        import numpy as np

        ckpt = Checkpoint.from_pytree_async(
            {"w": np.full(1 << 18, 3.0, np.float32)}, use_orbax=False)
        clone = pickle.loads(pickle.dumps(ckpt))
        # The reconstructed handle reads a complete directory.
        assert float(clone.to_pytree()["w"][0]) == 3.0

    def test_flush_pending_writes(self):
        import numpy as np

        from ray_tpu.train.checkpoint import flush_pending_writes

        Checkpoint.from_pytree_async({"w": np.zeros(16)},
                                     use_orbax=False)
        flush_pending_writes()
        # Idempotent with nothing in flight.
        assert flush_pending_writes() == 0


class TestHostCollective:
    """ISSUE-5 tentpole train wiring: the executor forms a host-DCN
    collective group over the gang and host_allreduce_async overlaps
    the sync with the next step's work."""

    def test_host_allreduce_async_in_train_loop(self, ray_shared,
                                                tmp_path):
        def loop(config):
            import numpy as np

            from ray_tpu import train

            ctx = train.get_context()
            work = train.host_allreduce_async(
                np.full(8, float(ctx.get_world_rank() + 1), np.float32))
            # ... next step's input pipeline would run here ...
            summed = work.wait(60)
            train.report({"sum": float(summed[0]),
                          "rank": ctx.get_world_rank()})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(name="hostcol",
                                 storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["sum"] == 3.0      # ranks 1+2

    def test_host_allreduce_single_rank_identity(self, ray_shared,
                                                 tmp_path):
        def loop(config):
            import numpy as np

            from ray_tpu import train

            out = train.host_allreduce(np.full(4, 5.0, np.float32))
            train.report({"v": float(out[0])})

        trainer = JaxTrainer(
            loop, scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(name="hostcol1",
                                 storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["v"] == 5.0


@pytest.mark.skipif(
    __import__("ray_tpu._private.jax_compat",
               fromlist=["is_legacy"]).is_legacy(),
    reason="legacy jax: the CPU backend has no multiprocess "
    "computations (jax.distributed global mesh needs current jax)")
class TestMultiHostJax:
    def test_jax_distributed_global_mesh_psum(self, ray_shared, tmp_path):
        """Two train workers = two jax processes forming ONE global mesh
        via the JaxBackend rendezvous; a cross-process collective
        (global-array sum) produces the allreduced value on every rank
        (the multi-host path of SURVEY §7 step 5, testable on CPU)."""
        def loop(config):
            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            from ray_tpu.train import get_context, report

            assert jax.process_count() == 2
            assert jax.device_count() >= 2
            rank = get_context().get_world_rank()
            mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
            arr = jax.make_array_from_callback(
                (2,), NamedSharding(mesh, P("data")),
                lambda idx: np.array([float(rank + 1)]))
            total = float(jax.jit(jnp.sum)(arr))   # cross-process reduce
            report({"total": total, "rank": rank})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(name="mh", storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None
        assert result.metrics["total"] == 3.0     # 1 (rank0) + 2 (rank1)

    def test_8b_recipe_real_step_two_processes(self, ray_shared,
                                               tmp_path):
        """The llama3-8b RECIPE path — dp x fsdp x tp mesh, logical-axis
        shardings, sharded_init / sharded_train_step — executed for REAL
        across two jax processes (4 local CPU devices each, one global
        8-device mesh via the JaxBackend rendezvous), tiny dims, with
        numerics asserted: loss decreases over steps.  This is the
        multi-host half of SURVEY §7 step 5 that the abstract 8B trace
        cannot cover."""
        def loop(config):
            import jax

            # Before any device query in this worker process.
            from ray_tpu._private.config import ensure_cpu_devices

            ensure_cpu_devices(4)
            import jax.numpy as jnp
            import numpy as np

            from ray_tpu.models import llama
            from ray_tpu.parallel.mesh import MeshConfig, create_mesh
            from ray_tpu.train import report
            from ray_tpu.train import step as train_step

            assert jax.process_count() == 2
            assert len(jax.devices()) == 8, jax.devices()
            # The 8B recipe's axes at dryrun scale: dp x fsdp x tp.
            mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
            cfg = llama.LlamaConfig(
                vocab_size=256, dim=128, n_layers=2, n_heads=4,
                n_kv_heads=2, ffn_dim=256, max_seq=64, remat=True)
            opt = train_step.default_optimizer(lr=1e-2, warmup=1,
                                               total_steps=20)
            state = train_step.sharded_init(jax.random.PRNGKey(0), cfg,
                                            opt, mesh)
            step = train_step.sharded_train_step(cfg, opt, mesh)
            b_sh = train_step.batch_shardings(mesh)
            rng = np.random.RandomState(1)
            toks = rng.randint(0, 256, (4, 64)).astype(np.int32)
            batch = {
                "inputs": jax.make_array_from_callback(
                    (4, 64), b_sh, lambda idx: toks[idx]),
                "targets": jax.make_array_from_callback(
                    (4, 64), b_sh, lambda idx: toks[idx]),
            }
            losses = []
            with jax.set_mesh(mesh):
                for _ in range(3):
                    state, m = step(state, batch)
                    losses.append(float(m["loss"]))
            report({"losses": losses})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(name="recipe8b",
                                 storage_path=str(tmp_path)))
        result = trainer.fit()
        assert result.error is None, result.error
        losses = result.metrics["losses"]
        assert losses[-1] < losses[0], losses


def _resumable_loop(config):
    """Checkpoint-per-step loop whose rank 1 hard-kills itself ONCE at the
    configured step (marker file arms the kill exactly one incarnation)."""
    import os
    import signal
    import time

    from ray_tpu import train

    ctx = train.get_context()
    ckpt = train.get_checkpoint()
    start = ckpt.to_dict()["step"] + 1 if ckpt else 0
    for i in range(start, config["total_steps"]):
        marker = config.get("kill_marker")
        if (marker and i == config.get("kill_at", -1)
                and ctx.get_world_rank() == 1
                and not os.path.exists(marker)):
            open(marker, "w").close()
            os.kill(os.getpid(), signal.SIGKILL)
        if config.get("progress_dir"):
            with open(os.path.join(config["progress_dir"],
                                   f"rank{ctx.get_world_rank()}"),
                      "w") as f:
                f.write(f"{ctx.get_node_id()} {i}")
        train.report({"step": i, "start": start,
                      "rank": ctx.get_world_rank()},
                     checkpoint=Checkpoint.from_dict({"step": i}))
        if config.get("step_sleep_s"):
            time.sleep(config["step_sleep_s"])


class TestTrainElasticity:
    """Chaos tests for the LEGACY group-restart path (ray:
    backend_executor.py:740-756 _restart + max_failures): the round-4
    verdict's most under-tested claim — recovery is implemented but no
    test killed anything mid-fit().  Pinned to RAY_TPU_ELASTIC=0 since
    round 12: the elastic membership-epoch path (default) turns these
    kills into shrink-and-continue (tests/test_train_elastic.py); these
    tests keep the restart loop honest for the kill-switch A/B."""

    def test_worker_sigkill_restarts_and_resumes(self, ray_shared,
                                                 tmp_path, monkeypatch):
        """SIGKILL rank 1 mid-run: the group restarts within
        max_failures and the retry resumes from the NEWEST checkpoint
        (not the run's original resume point)."""
        monkeypatch.setenv("RAY_TPU_ELASTIC", "0")
        marker = tmp_path / "killed_once"
        # step_sleep paces the loop to the executor's poll cadence so the
        # checkpointed rounds 0-2 EMIT before the kill; an instant loop
        # dies with its reports still queued worker-side and the retry
        # legitimately restarts from scratch.
        trainer = JaxTrainer(
            _resumable_loop,
            train_loop_config={"total_steps": 6, "kill_at": 3,
                               "step_sleep_s": 0.4,
                               "kill_marker": str(marker)},
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(
                name="chaos_worker_kill", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1)))
        result = trainer.fit()
        assert marker.exists(), "kill never armed - test is vacuous"
        assert result.error is None, result.error
        assert result.metrics["step"] == 5
        # The retry resumed from the newest full-round checkpoint: some
        # report in the history carries start > 0.  A replay-from-zero
        # (the pre-round-5 behavior: _restart reused the ORIGINAL
        # resume_checkpoint) would report start == 0 everywhere.
        starts = {m.get("start") for m in result.metrics_history}
        assert any(s > 0 for s in starts if s is not None), starts

    def test_max_failures_exhausted_surfaces_error(self, ray_shared,
                                                   tmp_path, monkeypatch):
        """Unconditional rank-1 suicide: restarts stop after
        max_failures and the failure surfaces in Result.error."""
        monkeypatch.setenv("RAY_TPU_ELASTIC", "0")

        def always_dies(config):
            import os
            import signal

            from ray_tpu import train

            ctx = train.get_context()
            if ctx.get_world_rank() == 1:
                os.kill(os.getpid(), signal.SIGKILL)
            train.report({"step": 0})

        trainer = JaxTrainer(
            always_dies,
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(
                name="chaos_exhaust", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1)))
        result = trainer.fit()
        assert result.error is not None
        msg = str(result.error)
        assert "died" in msg or "worker" in msg, msg


def test_node_agent_kill_mid_fit(tmp_path, monkeypatch):
    """Kill the NODE AGENT hosting the train workers mid-fit(): worker
    death propagates, the group restarts on surviving nodes, and the run
    completes from the latest checkpoint (the reference's recovery unit
    — lose a host, keep the run).  Legacy-path pin, see class note."""
    import threading
    import time

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    monkeypatch.setenv("RAY_TPU_ELASTIC", "0")

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)
        progress = tmp_path / "progress"
        progress.mkdir()
        trainer = JaxTrainer(
            _resumable_loop,
            train_loop_config={"total_steps": 8, "step_sleep_s": 0.3,
                               "progress_dir": str(progress)},
            scaling_config=ScalingConfig(num_workers=2,
                                         num_cpus_per_worker=0.5),
            run_config=RunConfig(
                name="chaos_node_kill", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=2)))
        box = {}

        def run_fit():
            box["result"] = trainer.fit()

        t = threading.Thread(target=run_fit, daemon=True)
        t.start()
        # Wait for both ranks to make progress, then kill the agent of
        # whichever NON-HEAD node hosts rank 0.
        deadline = time.monotonic() + 120
        victim = None
        while time.monotonic() < deadline and victim is None:
            f = progress / "rank0"
            if f.exists():
                node_id, step = f.read_text().split()
                if int(step) >= 1:
                    victim = next((n for n in (n1, n2)
                                   if n["node_id"] == node_id), None)
            time.sleep(0.2)
        assert victim is not None, "rank0 never reported progress"
        cluster.kill_node(victim)
        t.join(timeout=240)
        assert not t.is_alive(), "fit() wedged after node kill"
        result = box["result"]
        assert result.error is None, result.error
        assert result.metrics["step"] == 7
        starts = {m.get("start") for m in result.metrics_history}
        assert any(s > 0 for s in starts if s is not None), starts
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
