"""Sanitizer hammer for the shm arena (native/store.cc).

The reference's plasma/raylet concurrency is guarded by TSAN CI (SURVEY
§5); this arena's equivalent risk surface — the in-arena robust mutex,
the pid-attributed pin table, and the crash sweep — is exercised here by
a standalone hammer binary (native/store_hammer.cc) compiled WHOLE with
-fsanitize=thread: writers churn generations while readers verify fill
patterns under pins, and the orchestrator SIGKILLs readers (sometimes
mid-mutex — the EOWNERDEAD/consistent path) and sweeps their pins.
A sanitizer report fails the run via exitcode=66; pattern corruption or
a stranded pin exits 65.
"""
import os
import subprocess
import sys

import pytest

_NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def _build_hammer(san: str) -> str | None:
    from ray_tpu._private.native_store import SANITIZE_FLAGS

    out = os.path.join(_NATIVE, "build", f"store_hammer_{san}")
    src = os.path.join(_NATIVE, "store_hammer.cc")
    store = os.path.join(_NATIVE, "store.cc")
    try:
        # Not a shared lib: the hammer links store.cc directly so every
        # frame is instrumented (a sanitized .so dlopen'd into plain
        # python is not a supported TSAN configuration).
        import fcntl
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            newest = max(os.path.getmtime(src), os.path.getmtime(store))
            if not (os.path.exists(out)
                    and os.path.getmtime(out) >= newest):
                cmd = ["g++", "-std=c++17", *SANITIZE_FLAGS[san],
                       "-o", out + ".tmp", src, store,
                       "-lpthread", "-lrt"]
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=300)
                os.replace(out + ".tmp", out)
        return out
    except (subprocess.CalledProcessError, OSError):
        return None


def _run_hammer(san: str, env_extra: dict) -> None:
    binary = _build_hammer(san)
    if binary is None:
        pytest.skip(f"toolchain cannot build -fsanitize={san}")
    shm = f"rthammer_{san}_{os.getpid()}"
    env = {**os.environ, **env_extra}
    try:
        proc = subprocess.run(
            [binary, "orchestrate", shm, "2", "3", "6"],
            capture_output=True, text=True, timeout=240, env=env)
    finally:
        try:
            os.unlink(f"/dev/shm/{shm}")
        except OSError:
            pass
    assert proc.returncode == 0, (
        f"hammer rc={proc.returncode}\nstdout: {proc.stdout[-2000:]}\n"
        f"stderr: {proc.stderr[-4000:]}")


def test_hammer_tsan():
    _run_hammer("tsan", {
        "TSAN_OPTIONS": "exitcode=66 halt_on_error=1"})


def test_hammer_asan():
    _run_hammer("asan", {
        "ASAN_OPTIONS": "exitcode=66 abort_on_error=0"})
