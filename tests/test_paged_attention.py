"""Paged-KV decode: kernel numerics, engine equivalence, long context.

The serving-side answer to SURVEY §7's "bucketed shapes/paged KV via
Pallas" hard part (reference analog: vLLM paged attention under ray
Serve; ray itself has no attention op).  Kernel runs in interpret mode
on CPU — same code path as the TPU build.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_kernel_matches_reference_across_page_counts():
    from ray_tpu.ops.paged_attention import (paged_decode_attention,
                                             paged_decode_reference)

    rng = np.random.default_rng(0)
    B, kvh, rep, hd, kt = 4, 2, 2, 32, 4
    page, n_pages, maxp = 8, 20, 4
    q = jnp.asarray(rng.normal(size=(B, kvh, rep, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_pages, kvh, page, hd)),
                     jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_pages, kvh, page, hd)),
                     jnp.float32)
    ktail = jnp.asarray(rng.normal(size=(B, kvh, kt, hd)), jnp.float32)
    vtail = jnp.asarray(rng.normal(size=(B, kvh, kt, hd)), jnp.float32)
    table = np.zeros((B, maxp), np.int32)
    ids = iter(range(1, n_pages))
    for b in range(B):
        for p in range(maxp):
            table[b, p] = next(ids)
    table = jnp.asarray(table)
    # Block starts spanning 0..4 pages incl. boundaries; pos = ts + j.
    ts = jnp.asarray([0, 7, 8, 27], jnp.int32)
    pos = ts + 2
    args = (q, kp, vp, ktail, vtail, table, pos, ts)
    o_ref = paged_decode_reference(*args)
    o = paged_decode_attention(*args)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref),
                               atol=1e-5)


def test_merge_tail_roundtrip():
    """merge_tail_pages + a fresh-block attend == attending the same
    rows from the tail (the block-boundary invariant)."""
    from ray_tpu.ops.paged_attention import (merge_tail_pages,
                                             paged_decode_attention)

    rng = np.random.default_rng(1)
    B, kvh, rep, hd, kt = 2, 2, 1, 16, 4
    page, n_pages, maxp = 8, 10, 2
    q = jnp.asarray(rng.normal(size=(B, kvh, rep, hd)), jnp.float32)
    kp = jnp.asarray(rng.normal(size=(n_pages, kvh, page, hd)),
                     jnp.float32)
    vp = jnp.asarray(rng.normal(size=(n_pages, kvh, page, hd)),
                     jnp.float32)
    ktail = jnp.asarray(rng.normal(size=(B, kvh, kt, hd)), jnp.float32)
    vtail = jnp.asarray(rng.normal(size=(B, kvh, kt, hd)), jnp.float32)
    table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    ts = jnp.asarray([3, 6], jnp.int32)
    pos = ts + (kt - 1)
    o_in_block = paged_decode_attention(q, kp, vp, ktail, vtail, table,
                                        pos, ts)
    # Merge the block, start a new one at ts' = pos + 1 with empty tail.
    kp2 = merge_tail_pages(kp, ktail, table, ts, kt)
    vp2 = merge_tail_pages(vp, vtail, table, ts, kt)
    empty = jnp.zeros_like(ktail)
    o_next = paged_decode_attention(q, kp2, vp2, empty, empty, table,
                                    pos, pos + 1)
    np.testing.assert_allclose(np.asarray(o_in_block),
                               np.asarray(o_next), atol=1e-5)


def test_kernel_clamps_runaway_idle_pos():
    """An idle slot's pos keeps advancing between reuses; the kernel must
    clamp rather than index past the table."""
    from ray_tpu.ops.paged_attention import paged_decode_attention

    B, kvh, rep, hd, kt = 2, 1, 1, 16, 2
    page, n_pages, maxp = 8, 4, 2
    q = jnp.ones((B, kvh, rep, hd), jnp.float32)
    kp = jnp.zeros((n_pages, kvh, page, hd), jnp.float32)
    vp = jnp.zeros((n_pages, kvh, page, hd), jnp.float32)
    ktail = jnp.ones((B, kvh, kt, hd), jnp.float32)
    vtail = jnp.ones((B, kvh, kt, hd), jnp.float32)
    table = jnp.zeros((B, maxp), jnp.int32)
    ts = jnp.asarray([3, 10_000], jnp.int32)   # slot 1 ran away
    o = paged_decode_attention(q, kp, vp, ktail, vtail, table, ts + 1,
                               ts)
    assert np.all(np.isfinite(np.asarray(o)))


def _engine(paged: bool, **kw):
    from ray_tpu.models import llama
    from ray_tpu.serve.llm import LLMEngine

    cfg = llama.llama_configs()["debug"]
    eng = LLMEngine(cfg, max_batch=4, max_len=kw.pop("max_len", 128),
                    seed=0, paged=paged, **kw)
    eng.start()
    return eng


def test_paged_engine_matches_dense_greedy():
    from ray_tpu._private.jax_compat import is_legacy

    if is_legacy():
        pytest.skip("legacy jax: dense-vs-paged greedy tokens diverge "
                    "on this build's CPU lowering (kernel-level tests "
                    "above still pin the paged path's numerics)")
    dense = _engine(False)
    paged = _engine(True, page_size=16)
    try:
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9],
                   [11, 12, 13, 14, 15, 16, 17], [2, 4]]
        fd = [dense.submit(p, max_new_tokens=12) for p in prompts]
        fp = [paged.submit(p, max_new_tokens=12) for p in prompts]
        for a, b in zip(fd, fp):
            assert a.result(timeout=120)["tokens"] == \
                b.result(timeout=120)["tokens"]
    finally:
        dense.stop()
        paged.stop()


def test_paged_pool_backpressure():
    """More concurrent requests than the page pool holds: admission
    blocks FIFO on the pool and every request still completes."""
    eng = _engine(True, page_size=16, kv_pages=5)   # 4 usable pages
    try:
        futs = [eng.submit([1, 2, 3], max_new_tokens=10)
                for _ in range(6)]
        res = [f.result(timeout=180)["tokens"] for f in futs]
        assert all(len(r) == 10 for r in res)
    finally:
        eng.stop()


def test_long_context_engine_no_dense_prealloc():
    """max_len=32768 with a small page pool: the engine must NOT
    preallocate dense per-slot windows (VERDICT round-2 item 1's done
    condition), and a request whose span crosses several pages decodes
    correctly."""
    from ray_tpu.models import llama

    cfg = llama.llama_configs()["debug"]
    eng = _engine(True, max_len=32768, page_size=64, kv_pages=9)
    try:
        # Pool memory is 9 pages x 64 rows — NOT slots x 32768:
        pool_rows = eng.cache["k"][0].shape[0] * eng.cache["k"][0].shape[2]
        assert pool_rows < 4 * 32768 // 10, pool_rows
        prompt = list(np.arange(1, 150) % (cfg.vocab_size - 1) + 1)
        out = eng.submit(prompt, max_new_tokens=40).result(timeout=300)
        assert len(out["tokens"]) == 40
        # Same prompt through a dense engine at a window that fits it —
        # greedy tokens must agree (the paged path is not approximate).
        dense = _engine(False, max_len=256)
        try:
            ref = dense.submit(prompt,
                               max_new_tokens=40).result(timeout=300)
        finally:
            dense.stop()
        assert out["tokens"] == ref["tokens"]
    finally:
        eng.stop()
