"""Memory monitor + OOM killing policy + profiling spans.

Mirrors ray: src/ray/common/memory_monitor_test.cc and the
worker_killing_policy tests (policy logic exercised directly — triggering
a real host OOM in CI is not safe, the same reason the reference tests
the policy against fake processes).
"""
import time
from dataclasses import dataclass, field

import pytest

from ray_tpu._private.memory_monitor import (MemoryMonitor,
                                             memory_usage_fraction,
                                             pick_oom_victim)


@dataclass
class FakeWorker:
    worker_id: str
    state: str
    is_device_worker: bool = False
    started_at: float = field(default_factory=time.monotonic)


def test_memory_usage_fraction_sane():
    frac = memory_usage_fraction()
    assert 0.0 <= frac <= 1.0
    # This test process is alive, so some memory is in use.
    assert frac > 0.0


def test_pick_victim_prefers_newest_leased_task_worker():
    old = FakeWorker("old", "leased", started_at=1.0)
    new = FakeWorker("new", "leased", started_at=2.0)
    actor = FakeWorker("actor", "actor", started_at=3.0)
    idle = FakeWorker("idle", "idle", started_at=4.0)
    assert pick_oom_victim([old, new, actor, idle]) is new


def test_pick_victim_spares_actors_while_tasks_remain():
    task_w = FakeWorker("t", "leased", started_at=1.0)
    actor = FakeWorker("a", "actor", started_at=99.0)
    assert pick_oom_victim([task_w, actor]) is task_w
    # Only actors left: newest actor goes.
    a1 = FakeWorker("a1", "actor", started_at=1.0)
    a2 = FakeWorker("a2", "actor", started_at=2.0)
    assert pick_oom_victim([a1, a2]) is a2


def test_pick_victim_never_kills_device_or_idle_workers():
    dev = FakeWorker("d", "leased", is_device_worker=True)
    idle = FakeWorker("i", "idle")
    starting = FakeWorker("s", "starting")
    assert pick_oom_victim([dev, idle, starting]) is None


def test_monitor_threshold_and_cooldown():
    mon = MemoryMonitor(threshold=0.5, min_kill_interval_s=100.0)
    assert not mon.should_kill(usage=0.4)
    assert mon.should_kill(usage=0.9)
    # Cooldown: an immediate second crossing does not kill again.
    assert not mon.should_kill(usage=0.99)


def test_profiling_spans_in_timeline():
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 2})
    with ray_tpu.profiling.profile("unit-test-span"):
        pass
    time.sleep(1.5)   # event flush loop period
    events = ray_tpu.timeline()
    states = {e["state"] for e in events
              if "unit-test-span" in (e.get("name") or "")}
    assert "PROFILE_BEGIN" in states and "PROFILE_END" in states
