"""Cluster flight recorder (ISSUE 10): always-on cross-process request
tracing with one connected timeline per serve request.

Covers the tentpole's acceptance shape end-to-end:
  - recorder mechanics: ring bound, kill switch, context nesting;
  - cross-process propagation: driver → actor → nested task share ONE
    trace_id with parent links intact;
  - a disaggregated prefill/decode serve request produces a single
    connected trace spanning router (driver), prefill replica and
    decode replica processes, with the KV-migration spans
    (kv_export → put → pull → kv_import) present, exported as valid
    Chrome trace JSON and the OTLP document shape;
  - harvest survives a SIGKILLed replica (chaos marker): the surviving
    side's spans collect cleanly — bounded, no hang, no corruption.

Engine tests run debug-scale fp32 on the CPU mesh (the
test_pd_disagg.py discipline).
"""
import json
import time

import pytest


@pytest.fixture(scope="module")
def small():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq=128, remat=False, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(7), cfg)
    return cfg, params


PROMPT = [(i * 7 + 3) % 127 + 1 for i in range(21)]


# ------------------------------------------------------------ recorder
def test_span_nesting_and_ids():
    from ray_tpu import tracing
    from ray_tpu._private import spans as impl

    with tracing.span("t.root") as root_attrs:
        root_ctx = tracing.current()
        root_attrs["k"] = 1
        with tracing.span("t.child"):
            child_ctx = tracing.current()
            tracing.emit("t.leaf", time.time())
    after = tracing.current()
    # Context restored outside the block (no leak into later work).
    assert after is None or after != child_ctx
    assert child_ctx[0] == root_ctx[0]          # same trace
    assert child_ctx[1] != root_ctx[1]          # own span id
    recs = {r["name"]: r for r in impl.snapshot(root_ctx[0])}
    assert set(recs) == {"t.root", "t.child", "t.leaf"}
    assert recs["t.child"]["par"] == root_ctx[1]
    assert recs["t.leaf"]["par"] == recs["t.child"]["sid"]
    assert recs["t.root"]["attrs"]["k"] == 1
    local = [{**r, "proc": "local"} for r in impl.snapshot(root_ctx[0])]
    from ray_tpu import tracing as t

    assert t.connected(local, root_ctx[0])


def test_ring_is_bounded_and_kill_switch_is_free():
    from ray_tpu._private import spans as impl

    cap = impl._CAPACITY
    before = impl.stats()["emitted"]
    for i in range(cap + 50):
        impl.emit("t.flood", time.time())
    st = impl.stats()
    assert st["buffered"] <= cap
    assert st["emitted"] >= before + cap + 50
    # Kill switch: no records, context manager still yields.
    impl.set_enabled(False)
    try:
        n0 = impl.stats()["emitted"]
        with impl.span("t.off") as sp:
            sp["x"] = 1
        impl.emit("t.off2", time.time())
        assert impl.stats()["emitted"] == n0
        import os

        assert os.environ["RAY_TPU_TRACE"] == "0"
    finally:
        impl.set_enabled(True)


def test_control_verb_roundtrips_msgpack():
    import msgpack

    from ray_tpu._private import spans as impl

    # Exotic attr values must be coerced, never poison the harvest.
    impl.emit("t.attr", time.time(),
              attrs={"obj": object(), "f": 1.5, "b": True, "s": "x",
                     "n": None})
    reply = impl.control({"op": "collect"})
    packed = msgpack.packb(reply, use_bin_type=True)
    back = msgpack.unpackb(packed, raw=False)
    rec = next(r for r in back["spans"] if r["name"] == "t.attr")
    assert rec["attrs"]["f"] == 1.5 and rec["attrs"]["b"] is True
    assert isinstance(rec["attrs"]["obj"], str)


# ------------------------------------------------- cross-process traces
def test_driver_actor_nested_task_share_one_trace(ray_shared):
    import ray_tpu
    from ray_tpu import tracing

    @ray_tpu.remote
    def nested(x):
        return x * 2

    @ray_tpu.remote
    class Middle:
        def go(self, x):
            return ray_tpu.get(nested.remote(x)) + 1

    a = Middle.remote()
    with tracing.span("t.req") as _:
        ctx = tracing.current()
        out = ray_tpu.get(a.go.remote(3))
    assert out == 7
    spans = tracing.harvest(trace_id=ctx[0])
    names = {s["name"] for s in spans}
    assert "t.req" in names
    assert any(n.startswith("actor.go") for n in names), names
    assert any(n.startswith("task.") for n in names), names
    # One trace, parent links intact, spanning >= 2 processes.
    assert tracing.connected(spans, ctx[0]), [
        (s["name"], s["sid"], s["par"]) for s in spans]
    assert len({s["proc"] for s in spans}) >= 2
    # Every span of the trace shares the trace_id by construction;
    # the actor's span must be a child of the driver's root span.
    root = next(s for s in spans if s["name"] == "t.req")
    actor_span = next(s for s in spans if s["name"].startswith("actor."))
    assert actor_span["par"] == root["sid"]


def test_collective_op_emits_phase_span(ray_shared):
    import numpy as np

    import ray_tpu
    from ray_tpu import tracing

    @ray_tpu.remote
    class Rank:
        def init(self, world, rank, name):
            from ray_tpu import collective

            collective.init_collective_group(world, rank,
                                             group_name=name)
            return True

        def reduce(self, name):
            from ray_tpu import collective

            return collective.allreduce(
                np.ones(8, np.float32), group_name=name).tolist()

    ranks = [Rank.remote() for _ in range(2)]
    ray_tpu.get([r.init.remote(2, i, "fr_g") for i, r in
                 enumerate(ranks)], timeout=120)
    with tracing.span("t.step") as _:
        ctx = tracing.current()
        outs = ray_tpu.get([r.reduce.remote("fr_g") for r in ranks],
                           timeout=120)
    assert all(o == [2.0] * 8 for o in outs)
    spans = tracing.harvest(trace_id=ctx[0])
    col = [s for s in spans if s["name"].startswith("collective.")]
    # Both ranks recorded their op with phase/byte accounting attrs.
    assert len(col) >= 2, [s["name"] for s in spans]
    assert all(s["attrs"].get("world") == 2 for s in col)
    assert all("schedule" in s["attrs"] for s in col)


# ------------------------------------------------------- engine anatomy
def _engine(small, **kw):
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = small
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("page_size", 8)
    kw.setdefault("steps_per_sync", 4)
    eng = LLMEngine(cfg, params, seed=0, paged=True, **kw)
    eng.start()
    return eng


def test_engine_request_stage_spans_and_histograms(small):
    from ray_tpu import tracing
    from ray_tpu._private import spans as impl
    from ray_tpu.utils import metrics as um

    eng = _engine(small, name="fr_eng")
    try:
        with tracing.span("t.serve") as _:
            ctx = tracing.current()
            out = eng.generate(PROMPT, max_new_tokens=8)
        assert len(out["tokens"]) == 8
        recs = [r for r in impl.snapshot(ctx[0])]
        names = [r["name"] for r in recs]
        for want in ("llm.queue", "llm.prefill", "llm.first_token",
                     "llm.decode_window"):
            assert want in names, names
        # 8 tokens at 4 steps/sync: first token from prefill, then the
        # decode windows that produced the remaining 7.
        assert names.count("llm.decode_window") >= 2
        pre = next(r for r in recs if r["name"] == "llm.prefill")
        assert pre["attrs"]["prompt_tokens"] == len(PROMPT)
        ft = next(r for r in recs if r["name"] == "llm.first_token")
        assert ft["attrs"]["ttft_ms"] >= 0
        # Latency histograms observed with per-stage tags.
        h = um.get_or_create(um.Histogram, "serve_request_ttft_ms")
        snap = h.snapshot()
        assert any(v["tags"].get("engine") == "fr_eng"
                   for v in snap["values"])
        st = um.get_or_create(um.Histogram, "serve_request_stage_ms")
        stages = {v["tags"]["stage"] for v in st.snapshot()["values"]
                  if v["tags"].get("engine") == "fr_eng"}
        assert {"queue", "prefill", "decode"} <= stages
    finally:
        eng.stop()


def test_engine_kill_switch_same_run(small):
    """RAY_TPU_TRACE=0 semantics mid-process: requests served with the
    recorder off emit zero spans; flipping it back restores them — the
    same-run A/B the bench overhead row rides on."""
    from ray_tpu import tracing
    from ray_tpu._private import spans as impl

    eng = _engine(small, name="fr_ab")
    try:
        impl.set_enabled(False)
        n0 = impl.stats()["emitted"]
        with tracing.span("t.off"):
            eng.generate(PROMPT, max_new_tokens=4)
        assert impl.stats()["emitted"] == n0
        impl.set_enabled(True)
        with tracing.span("t.on") as _:
            ctx = tracing.current()
            eng.generate(PROMPT[:12], max_new_tokens=4)
        assert any(r["name"] == "llm.decode_window"
                   for r in impl.snapshot(ctx[0]))
    finally:
        impl.set_enabled(True)
        eng.stop()


# --------------------------------------------------- serve PD-disagg
@pytest.fixture
def serve_ray(small):
    import ray_tpu
    from ray_tpu import serve

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    serve.start()
    yield serve
    serve.shutdown()


def _pd_app(serve, cfg, *, decode_replicas=1, seed=11):
    from ray_tpu.serve.llm import LLMServer

    ekw = dict(max_batch=2, max_len=64, page_size=8, steps_per_sync=4,
               seed=seed)
    Decode = serve.deployment(LLMServer).options(
        name="decode", num_replicas=decode_replicas,
        max_ongoing_requests=4)
    decode_app = Decode.bind(cfg, role="decode", **ekw)
    Prefill = serve.deployment(LLMServer).options(
        name="prefill", num_replicas=1, max_ongoing_requests=4)
    return Prefill.bind(cfg, role="prefill",
                        decode_deployment=decode_app, **ekw)


def test_pd_disagg_one_connected_trace_three_processes(serve_ray, small):
    """The acceptance criterion: one serve request under disaggregated
    prefill/decode produces a SINGLE connected trace (shared trace_id,
    parent links intact) spanning the router process, the prefill
    replica and the decode replica, with the KV-migration spans
    present — exported as valid Chrome trace JSON and the OTLP
    document shape."""
    from ray_tpu import tracing

    cfg, _params = small
    h = serve_ray.run(_pd_app(serve_ray, cfg), name="fr_pd",
                      route_prefix="/frpd")
    try:
        with tracing.span("t.pd_request") as _:
            ctx = tracing.current()
            out = h.remote({"prompt": PROMPT[:13],
                            "max_new_tokens": 6}).result(timeout_s=300)
        assert out.get("disagg") is True
        assert len(out["tokens"]) == 6
        deadline = time.time() + 60
        while True:
            spans = tracing.harvest(trace_id=ctx[0])
            names = {s["name"] for s in spans}
            wanted = {"t.pd_request", "serve.route", "serve.kv_put",
                      "serve.kv_pull", "llm.kv_export", "llm.kv_import",
                      "llm.prefill", "llm.decode_window"}
            if wanted <= names or time.time() > deadline:
                break
            time.sleep(0.5)     # export-thread spans land async
        assert wanted <= names, sorted(names)
        # Both replica hops execute as Replica.handle_request (the
        # deployment method name rides as an argument): one span on the
        # prefill replica, one on the decode replica.
        handler_procs = {s["proc"] for s in spans
                         if s["name"] == "actor.handle_request"}
        assert len(handler_procs) >= 2, sorted(
            (s["name"], s["proc"]) for s in spans)
        # ONE connected tree across >= 3 processes.
        assert tracing.connected(spans, ctx[0]), [
            (s["name"], s["proc"], s["sid"], s["par"]) for s in spans]
        procs = {s["proc"] for s in spans}
        assert len(procs) >= 3, procs
        # Valid Chrome trace JSON: every span an X event, json-clean.
        chrome = tracing.chrome_trace(spans)
        chrome2 = json.loads(json.dumps(chrome))
        assert len(chrome2["traceEvents"]) == len(spans)
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in chrome2["traceEvents"])
        # Valid OTLP document shape: fixed-width hex ids, one scope.
        otlp = json.loads(json.dumps(tracing.otlp_document(spans)))
        oss = otlp["resourceSpans"][0]["scopeSpans"][0]["spans"]
        assert len(oss) == len(spans)
        assert all(len(s["traceId"]) == 32 and len(s["spanId"]) == 16
                   for s in oss)
        tid32 = {s["traceId"] for s in oss}
        assert len(tid32) == 1
    finally:
        serve_ray.delete("fr_pd")


# ------------------------------------------------------------- chaos
@pytest.mark.chaos
def test_harvest_survives_sigkilled_replica(serve_ray, small):
    """A replica SIGKILLed mid-request: the requeued request completes
    on the survivor, and a cluster-wide harvest right after returns the
    surviving side's spans cleanly — bounded time, no hang, no buffer
    corruption (the dead worker costs one bounded fan-out timeout)."""
    import ray_tpu
    from ray_tpu import tracing
    from ray_tpu._private import failpoints

    cfg, _params = small

    class Echo:
        def __call__(self, request):
            return {"ok": True, "pid": __import__("os").getpid()}

    Dep = serve_ray.deployment(Echo).options(
        name="echo", num_replicas=2, max_ongoing_requests=4)
    h = serve_ray.run(Dep.bind(), name="fr_chaos",
                      route_prefix="/frchaos")
    try:
        # Warm both replicas, then arm a one-shot crash cluster-wide.
        for _ in range(4):
            assert h.remote({"q": 1}).result(timeout_s=120)["ok"]
        w = ray_tpu._private.worker.global_worker()
        w.call(w.controller_addr, "failpoints",
               {"op": "set", "spec": "serve.replica_call=nth:1+crash",
                "broadcast": True}, timeout=30.0)
        with tracing.span("t.chaos") as _:
            ctx = tracing.current()
            out = h.remote({"q": 2}).result(timeout_s=120)
        assert out["ok"]
        t0 = time.time()
        spans = tracing.harvest(timeout=30.0)
        elapsed = time.time() - t0
        assert elapsed < 45, elapsed
        mine = [s for s in spans if s["tid"] == ctx[0]]
        names = {s["name"] for s in mine}
        assert "t.chaos" in names and "serve.route" in names, names
        # The survivor's execution span made it out.
        assert any(n.startswith("actor.handle_request")
                   for n in names), names
    finally:
        failpoints.reset()
        try:
            w = ray_tpu._private.worker.global_worker()
            w.call(w.controller_addr, "failpoints",
                   {"op": "clear", "broadcast": True}, timeout=30.0)
        except Exception:  # noqa: BLE001 - best-effort disarm
            pass
        serve_ray.delete("fr_chaos")
