"""Placement group + scheduling strategy tests
(analog of ray: python/ray/tests/test_placement_group*.py)."""
import pytest


def test_pg_create_ready(ray_shared):
    import ray_tpu
    from ray_tpu.utils import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)
    locs = pg.bundle_locations()
    assert len(locs) == 2
    remove_placement_group(pg)


def test_pg_task_scheduling(ray_shared):
    import ray_tpu
    from ray_tpu.utils import (PlacementGroupSchedulingStrategy,
                               placement_group, remove_placement_group)

    pg = placement_group([{"CPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id

    strat = PlacementGroupSchedulingStrategy(pg,
                                             placement_group_bundle_index=0)
    node = ray_tpu.get(where.options(
        scheduling_strategy=strat, num_cpus=1).remote())
    assert node == pg.bundle_locations()[0]
    remove_placement_group(pg)


def test_pg_actor(ray_shared):
    import ray_tpu
    from ray_tpu.utils import (PlacementGroupSchedulingStrategy,
                               placement_group, remove_placement_group)

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote
    class A:
        def node(self):
            return ray_tpu.get_runtime_context().node_id

    a = A.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
        pg, placement_group_bundle_index=0)).remote()
    assert ray_tpu.get(a.node.remote()) == pg.bundle_locations()[0]
    del a
    remove_placement_group(pg)


def test_pg_invalid(ray_shared):
    from ray_tpu.utils import placement_group

    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")
    with pytest.raises(ValueError):
        placement_group([])


def test_pg_infeasible_pending(ray_shared):
    """A PG demanding more than the cluster has stays PENDING."""
    from ray_tpu.utils import (placement_group, placement_group_table,
                               remove_placement_group)

    pg = placement_group([{"CPU": 64}], strategy="PACK")
    assert not pg.ready(timeout=1.5)
    states = {p["pg_id"]: p["state"] for p in placement_group_table()}
    assert states[pg.id] == "PENDING"
    remove_placement_group(pg)


def test_node_affinity(ray_shared):
    import ray_tpu
    from ray_tpu.utils import NodeAffinitySchedulingStrategy

    node_id = ray_tpu.nodes()[0]["node_id"]

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id

    got = ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id)).remote())
    assert got == node_id


def test_actor_pool(ray_shared):
    import ray_tpu
    from ray_tpu.utils import ActorPool

    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    pool = ActorPool([Sq.remote(), Sq.remote()])
    out = list(pool.map(lambda a, v: a.sq.remote(v), range(8)))
    assert out == [i * i for i in range(8)]


def test_queue(ray_shared):
    from ray_tpu.utils.queue import Empty, Queue

    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get(timeout=0.1)
