"""Placement group + scheduling strategy tests
(analog of ray: python/ray/tests/test_placement_group*.py)."""
import pytest


def test_pg_create_ready(ray_shared):
    import ray_tpu
    from ray_tpu.utils import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)
    locs = pg.bundle_locations()
    assert len(locs) == 2
    remove_placement_group(pg)


def test_pg_task_scheduling(ray_shared):
    import ray_tpu
    from ray_tpu.utils import (PlacementGroupSchedulingStrategy,
                               placement_group, remove_placement_group)

    pg = placement_group([{"CPU": 2}], strategy="STRICT_PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id

    strat = PlacementGroupSchedulingStrategy(pg,
                                             placement_group_bundle_index=0)
    node = ray_tpu.get(where.options(
        scheduling_strategy=strat, num_cpus=1).remote())
    assert node == pg.bundle_locations()[0]
    remove_placement_group(pg)


def test_pg_actor(ray_shared):
    import ray_tpu
    from ray_tpu.utils import (PlacementGroupSchedulingStrategy,
                               placement_group, remove_placement_group)

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.ready(timeout=30)

    @ray_tpu.remote
    class A:
        def node(self):
            return ray_tpu.get_runtime_context().node_id

    a = A.options(scheduling_strategy=PlacementGroupSchedulingStrategy(
        pg, placement_group_bundle_index=0)).remote()
    assert ray_tpu.get(a.node.remote()) == pg.bundle_locations()[0]
    del a
    remove_placement_group(pg)


def test_pg_invalid(ray_shared):
    from ray_tpu.utils import placement_group

    with pytest.raises(ValueError):
        placement_group([{"CPU": 1}], strategy="DIAGONAL")
    with pytest.raises(ValueError):
        placement_group([])


def test_pg_infeasible_pending(ray_shared):
    """A PG demanding more than the cluster has stays PENDING."""
    from ray_tpu.utils import (placement_group, placement_group_table,
                               remove_placement_group)

    pg = placement_group([{"CPU": 64}], strategy="PACK")
    assert not pg.ready(timeout=1.5)
    states = {p["pg_id"]: p["state"] for p in placement_group_table()}
    assert states[pg.id] == "PENDING"
    remove_placement_group(pg)


def test_pg_create_reports_ready_inline(ray_shared):
    """create_pg waits for the first reservation pass server-side, so a
    satisfiable PG's ready() needs no further RPC (the PG-churn fast
    path: create+remove is two driver round trips total)."""
    from ray_tpu.utils import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg._created          # reported CREATED in the create reply
    assert pg.ready(timeout=0.001)   # no RPC, no wait
    remove_placement_group(pg)


def test_pg_async_release_frees_capacity(ray_shared):
    """remove is posted (not awaited) and bundle release happens off the
    controller's reply path; a release must still wake pending
    schedulers promptly — back-to-back full-capacity churn would hang
    (or crawl at one heartbeat per cycle) if the retry event regressed."""
    from ray_tpu.utils import placement_group, remove_placement_group

    for _ in range(10):
        pg = placement_group([{"CPU": 4}], strategy="PACK")  # whole node
        assert pg.ready(timeout=30), "capacity from removed PG not freed"
        remove_placement_group(pg)


def test_pg_remove_flushed_at_driver_exit(ray_shared):
    """A remove_placement_group immediately before shutdown/exit must
    reach the controller (call_nowait is flushed at shutdown) — a
    dropped removal would leak the reservation cluster-wide forever."""
    import json
    import os
    import subprocess
    import sys
    import time

    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu.utils import placement_group_table

    addr = global_worker().controller_addr
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = f"""
import sys
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu.utils import placement_group, remove_placement_group
ray_tpu.init(address={addr!r})
pg = placement_group([{{"CPU": 1}}], strategy="PACK")
assert pg.ready(timeout=30)
print(pg.id, flush=True)
remove_placement_group(pg)
ray_tpu.shutdown()
"""
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    pg_id = out.stdout.split()[-1]
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        states = {p["pg_id"]: p["state"] for p in placement_group_table()}
        if states.get(pg_id, "REMOVED") == "REMOVED":
            return
        time.sleep(0.5)
    raise AssertionError(f"PG {pg_id} still {states.get(pg_id)} after "
                         "driver exit: the posted remove was dropped")


def test_pg_owner_reaped_on_driver_kill(ray_shared):
    """Non-detached PGs die with their driver: a SIGKILLed driver can't
    run its remove, so the controller probes PG owners and reaps (ray:
    job-scoped PG lifetime).  A lifetime="detached" PG survives."""
    import os
    import signal
    import subprocess
    import sys
    import time

    import ray_tpu
    from ray_tpu._private.worker import global_worker
    from ray_tpu.utils import placement_group_table

    addr = global_worker().controller_addr
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = f"""
import sys, os, time
sys.path.insert(0, {repo!r})
import ray_tpu
from ray_tpu.utils import placement_group
ray_tpu.init(address={addr!r})
owned = placement_group([{{"CPU": 0.5}}], strategy="PACK")
det = placement_group([{{"CPU": 0.5}}], strategy="PACK",
                      lifetime="detached")
assert owned.ready(timeout=30) and det.ready(timeout=30)
print(owned.id, det.id, flush=True)
time.sleep(600)   # hold until killed
"""
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True)
    try:
        owned_id, det_id = proc.stdout.readline().split()
    except ValueError:
        proc.kill()
        raise AssertionError("driver subprocess failed to create PGs")
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        states = {p["pg_id"]: p["state"] for p in placement_group_table()}
        if states.get(owned_id) == "REMOVED":
            break
        time.sleep(1)
    states = {p["pg_id"]: p["state"] for p in placement_group_table()}
    assert states.get(owned_id) == "REMOVED", \
        f"owned PG not reaped after driver kill: {states.get(owned_id)}"
    assert states.get(det_id) == "CREATED", \
        f"detached PG should survive: {states.get(det_id)}"
    from ray_tpu.utils import remove_placement_group
    from ray_tpu.utils.placement_group import PlacementGroup

    remove_placement_group(PlacementGroup(det_id, [], "PACK"))


def test_node_affinity(ray_shared):
    import ray_tpu
    from ray_tpu.utils import NodeAffinitySchedulingStrategy

    node_id = ray_tpu.nodes()[0]["node_id"]

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id

    got = ray_tpu.get(where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id)).remote())
    assert got == node_id


def test_actor_pool(ray_shared):
    import ray_tpu
    from ray_tpu.utils import ActorPool

    @ray_tpu.remote
    class Sq:
        def sq(self, x):
            return x * x

    pool = ActorPool([Sq.remote(), Sq.remote()])
    out = list(pool.map(lambda a, v: a.sq.remote(v), range(8)))
    assert out == [i * i for i in range(8)]


def test_queue(ray_shared):
    from ray_tpu.utils.queue import Empty, Queue

    q = Queue(maxsize=4)
    q.put("a")
    q.put("b")
    assert q.qsize() == 2
    assert q.get() == "a"
    assert q.get() == "b"
    with pytest.raises(Empty):
        q.get(timeout=0.1)
