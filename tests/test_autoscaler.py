"""Autoscaler tests with the local-process NodeProvider.

Mirrors ray: FakeMultiNodeProvider-based autoscaler tests
(python/ray/tests/test_autoscaler_fake_multinode.py) — nodes are local
agent processes (SURVEY §4 "fakes" row).
"""
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


def test_autoscaler_scales_up_and_down(rt):
    from ray_tpu._private.worker import global_worker
    from ray_tpu.autoscaler import (AutoscalerConfig, LocalNodeProvider,
                                    StandardAutoscaler, request_resources)

    provider = LocalNodeProvider(global_worker().controller_addr)
    config = AutoscalerConfig(min_workers=0, max_workers=2,
                              idle_timeout_s=3.0, update_interval_s=0.5,
                              worker_node_config={"resources": {"CPU": 2}})
    scaler = StandardAutoscaler(provider, config)
    scaler.start()
    try:
        # Demand beyond the head node's 4 CPUs → scale up.
        request_resources(num_cpus=6)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if len(provider.non_terminated_nodes()) >= 1 and \
                    len([n for n in ray_tpu.nodes()
                         if n["state"] == "ALIVE"]) >= 2:
                break
            time.sleep(0.3)
        alive = [n for n in ray_tpu.nodes() if n["state"] == "ALIVE"]
        assert len(alive) >= 2, f"no scale-up: {alive}"
        assert ray_tpu.cluster_resources().get("CPU", 0) >= 6

        # Drop the demand floor → idle nodes terminate after the timeout.
        request_resources(num_cpus=0)
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            if not provider.non_terminated_nodes():
                break
            time.sleep(0.5)
        assert not provider.non_terminated_nodes(), "no scale-down"
    finally:
        scaler.stop()
        for pid in provider.non_terminated_nodes():
            provider.terminate_node(pid)
