"""Continuous-batched LLM engine: numerics vs full forward, slot reuse,
concurrency, and the Serve deployment body.

Reference analog: serve LLM workloads (ray: release/serve_tests/) — here
correctness-tested at debug scale on CPU: incremental prefill+decode must
reproduce the full-context forward pass exactly (fp32).
"""
import concurrent.futures

import numpy as np
import pytest


@pytest.fixture(scope="module")
def small():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq=64, remat=False, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(7), cfg)
    return cfg, params


def _reference_greedy(params, cfg, prompt, n_new):
    """Full-context forward per step — the slow-but-sure decoder."""
    import jax.numpy as jnp

    from ray_tpu.models import llama

    toks = list(prompt)
    for _ in range(n_new):
        logits = llama.forward(params, jnp.asarray([toks]), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_decode_paths_agree(small):
    """The scanned (compile-flat) and unrolled (in-place cache) decode
    paths share one layer body and must produce identical logits and
    cache states step for step."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg, params = small
    b, S = 2, 32
    c_scan = llama.init_kv_cache(cfg, b, S)
    c_unr = llama.init_kv_cache_leaves(cfg, b, S)
    toks = jnp.asarray([3, 7], jnp.int32)
    for _ in range(4):
        l1, c_scan = llama.decode_step(params, c_scan, toks, cfg)
        l2, c_unr = llama.decode_step_unrolled(params, c_unr, toks, cfg)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   atol=1e-5, rtol=1e-5)
        for li in range(cfg.n_layers):
            np.testing.assert_allclose(np.asarray(c_scan["k"][li]),
                                       np.asarray(c_unr["k"][li]),
                                       atol=1e-5, rtol=1e-5)
        toks = jnp.argmax(l1, axis=-1).astype(jnp.int32)


def test_engine_matches_full_forward_greedy(small):
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = small
    eng = LLMEngine(cfg, params, max_batch=2, max_len=64)
    try:
        for prompt in ([5, 9, 2], [17, 3, 44, 8, 11, 23, 6]):
            got = eng.generate(prompt, max_new_tokens=8)
            assert got["tokens"] == _reference_greedy(
                params, cfg, prompt, 8), prompt
            assert got["ttft_s"] > 0 and got["total_s"] >= got["ttft_s"]
    finally:
        eng.stop()


def test_continuous_batching_oversubscribed(small):
    """More requests than slots: admission waits for free slots, every
    request completes, greedy results stay independent of batching."""
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = small
    eng = LLMEngine(cfg, params, max_batch=2, max_len=64)
    eng.start()
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
        futs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        results = [f.result(timeout=120) for f in futs]
        assert eng.completed == 5
        for p, r in zip(prompts, results):
            assert r["tokens"] == _reference_greedy(params, cfg, p, 6), p
    finally:
        eng.stop()


def test_eos_stops_generation(small):
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = small
    eng = LLMEngine(cfg, params, max_batch=1, max_len=64)
    try:
        free_run = eng.generate([5, 9, 2], max_new_tokens=8)
        eos = free_run["tokens"][2]
        stopped = eng.generate([5, 9, 2], max_new_tokens=8, eos_id=eos)
        assert stopped["tokens"] == free_run["tokens"][:3]
    finally:
        eng.stop()


def test_llm_server_deployment_body(small):
    import asyncio

    from ray_tpu.serve.llm import LLMServer

    cfg, params = small
    server = LLMServer(cfg, params=params, max_batch=2, max_len=64)
    try:
        async def drive():
            return await asyncio.gather(*[
                server({"prompt": [3, 1, 4], "max_new_tokens": 4})
                for _ in range(3)])

        results = asyncio.run(drive())
        assert all(len(r["tokens"]) == 4 for r in results)
        assert server.stats()["completed"] >= 3
    finally:
        server.engine.stop()
