"""Data library: transformations, streaming execution, splits, IO.

Mirrors the reference's data tests (ray: python/ray/data/tests/) run
against a single-node cluster.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


class TestBasics:
    def test_range_count_take(self, ray_shared):
        ds = rd.range(100, parallelism=4)
        assert ds.count() == 100
        rows = ds.take(5)
        assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]

    def test_from_items_schema(self, ray_shared):
        ds = rd.from_items([{"a": 1, "b": "x"}, {"a": 2, "b": "y"}])
        assert ds.count() == 2
        assert set(ds.columns()) == {"a", "b"}

    def test_map_filter_flatmap_fused(self, ray_shared):
        ds = (rd.range(20, parallelism=2)
              .map(lambda r: {"id": r["id"] * 2})
              .filter(lambda r: r["id"] % 4 == 0)
              .flat_map(lambda r: [r, r]))
        vals = sorted(r["id"] for r in ds.take_all())
        expect = sorted(v for v in range(0, 40, 2) if v % 4 == 0
                        for _ in (0, 1))
        assert vals == expect

    def test_map_batches_tasks(self, ray_shared):
        ds = rd.range(32, parallelism=4).map_batches(
            lambda b: {"id": b["id"] + 1}, batch_size=8)
        assert sorted(r["id"] for r in ds.take_all()) == list(range(1, 33))

    def test_map_batches_actor_udf(self, ray_shared):
        class AddConst:
            def __init__(self, c=100):
                self.c = c

            def __call__(self, batch):
                return {"id": batch["id"] + self.c}

        ds = rd.range(16, parallelism=2).map_batches(
            AddConst, concurrency=2, fn_constructor_args=(100,))
        assert sorted(r["id"] for r in ds.take_all()) == \
            list(range(100, 116))

    def test_add_select_drop_columns(self, ray_shared):
        ds = (rd.range(4).add_column("sq", lambda r: int(r["id"]) ** 2)
              .select_columns(["sq"]))
        assert sorted(r["sq"] for r in ds.take_all()) == [0, 1, 4, 9]


class TestReshaping:
    def test_repartition(self, ray_shared):
        ds = rd.range(100, parallelism=2).repartition(5).materialize()
        assert ds.num_blocks() == 5
        assert ds.count() == 100

    def test_random_shuffle_preserves_multiset(self, ray_shared):
        ds = rd.range(50, parallelism=2).random_shuffle(seed=7)
        vals = [r["id"] for r in ds.take_all()]
        assert sorted(vals) == list(range(50))
        assert vals != list(range(50))

    def test_sort(self, ray_shared):
        ds = rd.from_items([{"v": x} for x in [5, 3, 9, 1, 7]]).sort("v")
        assert [r["v"] for r in ds.take_all()] == [1, 3, 5, 7, 9]
        dsd = rd.from_items([{"v": x} for x in [5, 3, 9]]).sort(
            "v", descending=True)
        assert [r["v"] for r in dsd.take_all()] == [9, 5, 3]

    def test_limit_streams_early(self, ray_shared):
        ds = rd.range(1000, parallelism=8).limit(10)
        assert ds.count() == 10

    def test_union(self, ray_shared):
        a = rd.range(5)
        b = rd.range(5).map(lambda r: {"id": r["id"] + 100})
        u = a.union(b)
        assert u.count() == 10
        # transforms compose after union
        assert u.filter(lambda r: r["id"] >= 100).count() == 5

    def test_zip(self, ray_shared):
        a = rd.from_items([{"x": i} for i in range(4)])
        b = rd.from_items([{"y": i * 10} for i in range(4)])
        z = a.zip(b)
        rows = z.take_all()
        assert all(r["y"] == r["x"] * 10 for r in rows)


class TestDistributedSort:
    """Range-partitioned sort (ray: sort_task_spec.py map/reduce): no
    single O(dataset) merge task; output block count == input blocks."""

    def test_sort_many_blocks_ascending(self, ray_shared):
        import numpy as np

        rng = np.random.default_rng(0)
        vals = rng.permutation(500).tolist()
        ds = rd.from_items([{"v": int(x)} for x in vals],
                           parallelism=8).sort("v")
        mat = ds.materialize()
        assert [r["v"] for r in mat.take_all()] == sorted(vals)
        # Range partitioning produces one output block per range.
        assert mat.num_blocks() == 8

    def test_sort_many_blocks_descending(self, ray_shared):
        import numpy as np

        rng = np.random.default_rng(1)
        vals = rng.permutation(300).tolist()
        ds = rd.from_items([{"v": int(x)} for x in vals],
                           parallelism=6).sort("v", descending=True)
        assert [r["v"] for r in ds.take_all()] == \
            sorted(vals, reverse=True)

    def test_sort_string_keys(self, ray_shared):
        words = [f"w{i:03d}" for i in range(100)]
        import random

        random.Random(3).shuffle(words)
        ds = rd.from_items([{"s": w} for w in words],
                           parallelism=4).sort("s")
        assert [r["s"] for r in ds.take_all()] == sorted(words)

    def test_sort_skewed_duplicates(self, ray_shared):
        vals = [7] * 100 + [1] * 5 + [9] * 5
        ds = rd.from_items([{"v": v} for v in vals],
                           parallelism=5).sort("v")
        assert [r["v"] for r in ds.take_all()] == sorted(vals)


class TestBackpressure:
    def test_memory_budget_bounds_queues(self, ray_shared):
        """The resource manager keeps each operator's input queue under
        its share of the memory budget (ray: resource_manager.py:25)."""
        import time as _time

        import numpy as np

        from ray_tpu.data import logical as L
        from ray_tpu.data.executor import StreamingExecutor

        block_bytes = 512 * 1024

        def slow(batch):
            _time.sleep(0.05)
            return batch

        ds = (rd.range(16, parallelism=16)
              .map_batches(lambda b: {
                  "x": np.zeros((len(b["id"]), block_bytes // 8),
                                dtype=np.float64)})
              .map_batches(slow))
        budget = 4 * block_bytes
        ex = StreamingExecutor(ds._plan, memory_budget=budget)
        out = list(ex.execute())
        assert len(out) == 16
        # The slow op's input queue never held more than its share plus
        # one average block (admission estimate granularity).
        slow_idx = len(ex.ops) - 1
        share = budget / max(1, len([o for o in ex.ops if True]))
        assert ex.rm.hwm.get(slow_idx, 0) <= share + 2 * block_bytes

    def test_sizes_learned_from_owner_table(self, ray_shared):
        import ray_tpu

        @ray_tpu.remote
        def big():
            import numpy as np

            return np.zeros(300_000, dtype=np.uint8)

        ref = big.remote()
        ray_tpu.get(ref)
        from ray_tpu.experimental import object_sizes

        sz = object_sizes([ref])[0]
        assert sz is not None and sz >= 300_000


class TestGroupBy:
    def test_groupby_partitioned_output(self, ray_shared):
        """Keyed aggregation hash-partitions the reduce: many keys land
        across multiple output blocks, no single whole-key-space task."""
        items = [{"k": i % 50, "v": float(i)} for i in range(400)]
        ds = rd.from_items(items, parallelism=8)
        mat = ds.groupby("k").sum("v").materialize()
        assert mat.num_blocks() > 1
        got = {int(r["k"]): float(r["sum(v)"]) for r in mat.take_all()}
        expect = {}
        for it in items:
            expect[it["k"]] = expect.get(it["k"], 0.0) + it["v"]
        assert got == expect

    def test_groupby_sum_mean(self, ray_shared):
        items = [{"k": i % 3, "v": float(i)} for i in range(12)]
        ds = rd.from_items(items, parallelism=3)
        out = ds.groupby("k").sum("v").take_all()
        expect = {}
        for it in items:
            expect[it["k"]] = expect.get(it["k"], 0.0) + it["v"]
        got = {int(r["k"]): float(r["sum(v)"]) for r in out}
        assert got == expect

        mean_out = ds.groupby("k").mean("v").take_all()
        got_mean = {int(r["k"]): float(r["mean(v)"]) for r in mean_out}
        assert got_mean == {k: v / 4 for k, v in expect.items()}


class TestIteration:
    def test_iter_batches_sizes(self, ray_shared):
        ds = rd.range(100, parallelism=4)
        sizes = [len(b["id"]) for b in ds.iter_batches(batch_size=32)]
        assert sizes == [32, 32, 32, 4]
        sizes = [len(b["id"]) for b in
                 ds.iter_batches(batch_size=32, drop_last=True)]
        assert sizes == [32, 32, 32]

    def test_iter_batches_formats(self, ray_shared):
        ds = rd.range(10)
        pd_batches = list(ds.iter_batches(batch_size=None,
                                          batch_format="pandas"))
        assert sum(len(b) for b in pd_batches) == 10

    def test_local_shuffle(self, ray_shared):
        ds = rd.range(64, parallelism=2)
        flat = np.concatenate([
            b["id"] for b in ds.iter_batches(
                batch_size=8, local_shuffle_buffer_size=4,
                local_shuffle_seed=3)])
        assert sorted(flat.tolist()) == list(range(64))

    def test_iter_jax_batches(self, ray_shared):
        import jax.numpy as jnp

        ds = rd.range(32, parallelism=2)
        batches = list(ds.iter_jax_batches(batch_size=16))
        assert len(batches) == 2
        assert isinstance(batches[0]["id"], jnp.ndarray)

    def test_tensor_columns(self, ray_shared):
        arr = np.arange(24, dtype=np.float32).reshape(6, 4)
        ds = rd.from_numpy(arr, column="feat")
        out = ds.map_batches(lambda b: {"feat": b["feat"] * 2}).to_numpy()
        np.testing.assert_allclose(out["feat"], arr * 2)


class TestSplit:
    def test_split(self, ray_shared):
        parts = rd.range(40, parallelism=4).split(2)
        total = sum(p.count() for p in parts)
        assert total == 40

    def test_streaming_split_two_consumers(self, ray_shared):
        its = rd.range(40, parallelism=4).streaming_split(2)
        got = []
        for it in its:
            for b in it.iter_batches(batch_size=None):
                got.extend(b["id"].tolist())
        assert sorted(got) == list(range(40))


class TestIO:
    def test_parquet_roundtrip(self, ray_shared, tmp_path):
        p = str(tmp_path / "pq")
        rd.range(50, parallelism=2).write_parquet(p)
        back = rd.read_parquet(p)
        assert back.count() == 50
        assert sorted(r["id"] for r in back.take_all()) == list(range(50))

    def test_csv_roundtrip(self, ray_shared, tmp_path):
        p = str(tmp_path / "csv")
        rd.from_items([{"a": i, "b": i * 2} for i in range(10)],
                      parallelism=2).write_csv(p)
        back = rd.read_csv(p)
        assert back.count() == 10

    def test_read_text(self, ray_shared, tmp_path):
        f = tmp_path / "t.txt"
        f.write_text("hello\nworld\n")
        ds = rd.read_text(str(f))
        assert [r["text"] for r in ds.take_all()] == ["hello", "world"]

    def test_from_pandas_to_pandas(self, ray_shared):
        import pandas as pd

        df = pd.DataFrame({"x": [1, 2, 3]})
        out = rd.from_pandas(df).to_pandas()
        assert out["x"].tolist() == [1, 2, 3]


class TestDatasetCompatSurface:
    """Round-4 method-parity batch (ray: dataset.py public methods)."""

    def test_global_aggregations(self, ray_shared):
        ds = rd.from_items([{"v": x} for x in [4, 1, 3, 2]])
        assert ds.sum("v") == 10
        assert ds.min("v") == 1
        assert ds.max("v") == 4
        assert ds.mean("v") == 2.5
        assert abs(ds.std("v") - 1.29099) < 1e-4
        out = ds.aggregate(total=("v", "sum"), lo=("v", "min"),
                           n=("v", "count"))
        assert out == {"total": 10, "lo": 1, "n": 4}
        assert rd.from_items([{"v": 2}, {"v": 1}, {"v": 2}]).unique("v") \
            == [1, 2]

    def test_take_batch_and_random_sample(self, ray_shared):
        ds = rd.range(100)
        batch = ds.take_batch(10)
        assert len(next(iter(batch.values()))) == 10
        n = sum(1 for _ in rd.range(2000).random_sample(
            0.5, seed=7).iter_rows())
        assert 800 < n < 1200

    def test_randomize_block_order_preserves_rows(self, ray_shared):
        ds = rd.range(40, parallelism=8)
        rows = sorted(r["id"] for r in
                      ds.randomize_block_order(seed=3).iter_rows())
        assert rows == list(range(40))

    def test_split_at_indices_and_proportions(self, ray_shared):
        parts = rd.range(10).split_at_indices([3, 7])
        sizes = [p.count() for p in parts]
        assert sizes == [3, 4, 3]
        parts = rd.range(20).split_proportionately([0.25, 0.25])
        assert [p.count() for p in parts] == [5, 5, 10]
        train, test = rd.range(20).train_test_split(0.25)
        assert (train.count(), test.count()) == (15, 5)

    def test_schema_accessors_and_copy(self, ray_shared):
        ds = rd.from_items([{"a": 1, "b": "x"}])
        assert ds.names() == ["a", "b"]
        assert len(ds.types()) == 2
        cp = ds.copy()
        assert cp.take_all() == ds.take_all()
        from ray_tpu.data.context import DataContext

        assert isinstance(ds.context(), DataContext)

    def test_input_files(self, ray_shared, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        f = tmp_path / "part.parquet"
        pq.write_table(pa.table({"v": [1, 2]}), f)
        ds = rd.read_parquet(str(tmp_path))
        assert ds.input_files() == [str(f)]

    def test_to_refs(self, ray_shared):
        import numpy as np

        ds = rd.range(8, parallelism=2)
        nrefs = ds.to_numpy_refs()
        cols = ray_tpu.get(nrefs[0])
        assert isinstance(cols["id"], np.ndarray)
        arefs = ds.to_arrow_refs()
        assert sum(ray_tpu.get(r).num_rows for r in arefs) == 8

    def test_write_numpy_sql_webdataset(self, ray_shared, tmp_path):
        import sqlite3

        import numpy as np

        rd.range(6).write_numpy(str(tmp_path / "np"), column="id")
        arrs = [np.load(str(p)) for p in
                sorted((tmp_path / "np").iterdir())]
        assert sorted(np.concatenate(arrs).tolist()) == list(range(6))

        db = tmp_path / "t.db"
        conn = sqlite3.connect(db)
        conn.execute("CREATE TABLE t (v INTEGER)")
        conn.commit()
        conn.close()
        rd.from_items([{"v": i} for i in range(5)]).write_sql(
            "INSERT INTO t VALUES (?)",
            lambda: sqlite3.connect(db))
        conn = sqlite3.connect(db)
        assert sorted(r[0] for r in
                      conn.execute("SELECT v FROM t")) == list(range(5))
        conn.close()

        wds_dir = tmp_path / "wds"
        rd.from_items(
            [{"__key__": f"s{i}", "txt": f"hello{i}".encode()}
             for i in range(4)]).write_webdataset(str(wds_dir))
        back = rd.read_webdataset(str(wds_dir)).take_all()
        assert sorted(bytes(r["txt"]).decode() for r in back) \
            == [f"hello{i}" for i in range(4)]


class TestDataModuleSurface:
    """Round-4 module-level parity (ray: data/__init__ __all__)."""

    def test_ref_constructors(self, ray_shared):
        import pandas as pd
        import pyarrow as pa

        nref = ray_tpu.put(np.arange(4))
        assert rd.from_numpy_refs(nref).count() == 4
        pref = ray_tpu.put(pd.DataFrame({"a": [1, 2]}))
        assert [r["a"] for r in rd.from_pandas_refs(pref).take_all()] \
            == [1, 2]
        aref = ray_tpu.put(pa.table({"b": [3, 4, 5]}))
        assert rd.from_arrow_refs(aref).count() == 3

    def test_range_tensor_and_read_numpy(self, ray_shared, tmp_path):
        ds = rd.range_tensor(4, shape=(2, 2))
        rows = ds.take_all()
        assert rows[3]["data"].tolist() == [[3, 3], [3, 3]]
        rd.range(6).write_numpy(str(tmp_path), column="id")
        back = rd.read_numpy(str(tmp_path))
        total = sorted(
            v for r in back.take_all() for v in np.atleast_1d(r["data"]))
        assert total == list(range(6))
        assert back.input_files()
        assert rd.read_parquet_bulk is not None

    def test_custom_datasource_and_sink(self, ray_shared):
        class Tens(rd.Datasource):
            def get_read_tasks(self, parallelism):
                from ray_tpu.data.block import _rows_to_table

                def mk(i):
                    def read():
                        yield _rows_to_table(
                            [{"v": i * 10 + j} for j in range(2)])

                    return read

                return [mk(i) for i in range(parallelism)]

        ds = rd.read_datasource(Tens(), parallelism=3)
        assert ds.count() == 6

        collected = []

        class Collect(rd.Datasink):
            def write(self, block):
                from ray_tpu.data.block import BlockAccessor

                return BlockAccessor.for_block(block).num_rows()

            def on_write_complete(self, results):
                collected.extend(results)

        ds.write_datasink(Collect())
        assert sum(collected) == 6

    def test_actor_pool_strategy(self, ray_shared):
        class AddOne:
            def __call__(self, batch):
                return {"v": batch["v"] + 1}

        ds = rd.from_items([{"v": i} for i in range(8)]).map_batches(
            AddOne, compute=rd.ActorPoolStrategy(size=2), batch_size=2)
        assert sorted(r["v"] for r in ds.take_all()) == list(range(1, 9))

    def test_schema_and_progress_flag(self, ray_shared):
        import pyarrow as pa

        ds = rd.from_items([{"a": 1}])
        assert isinstance(ds.schema(), rd.Schema)
        assert isinstance(ds.schema(), pa.Schema)
        prev = rd.set_progress_bars(False)
        assert rd.set_progress_bars(prev) is False
