"""Prefix-cache + block-scheduler behavior of the paged LLM engine:
paged-vs-dense token parity with shared prefixes (cache on vs off
byte-identical under seeded greedy), COW divergence correctness,
refcount/eviction invariants after serving, preempt-restore
determinism, and oversubscription completing via preemption.

Debug-scale fp32 on the CPU mesh (no TPU needed) — same discipline as
test_llm_serve.py."""
import pytest


@pytest.fixture(scope="module")
def small():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq=128, remat=False, dtype=jnp.float32)
    params = llama.init_params(jax.random.PRNGKey(7), cfg)
    return cfg, params


def _engine(small, **kw):
    from ray_tpu.serve.llm import LLMEngine

    cfg, params = small
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("page_size", 8)
    kw.setdefault("steps_per_sync", 4)
    eng = LLMEngine(cfg, params, seed=0, paged=True, **kw)
    eng.start()
    return eng


SHARED = [(i * 7 + 3) % 127 + 1 for i in range(24)]   # 3 full pages


def test_shared_prefix_token_parity(small):
    """Requests sharing a long prompt prefix: prefix cache ON must
    produce byte-identical greedy tokens to cache OFF, while actually
    skipping the shared prefill (hit counters prove why)."""
    on = _engine(small, prefix_cache=True)
    off = _engine(small, prefix_cache=False)
    try:
        prompts = [SHARED + [40 + i, 41 + i, 42 + i] for i in range(4)]
        got_on = [on.generate(p, max_new_tokens=6) for p in prompts]
        got_off = [off.generate(p, max_new_tokens=6) for p in prompts]
        for a, b, p in zip(got_on, got_off, prompts):
            assert a["tokens"] == b["tokens"], p
        s_on, s_off = on.stats(), off.stats()
        assert s_on["prefix_hits"] >= 3
        assert s_on["prefix_hit_tokens"] >= 3 * len(SHARED)
        assert s_on["prefill_tokens"] < s_off["prefill_tokens"]
        assert s_off["prefix_hit_tokens"] == 0
    finally:
        on.stop()
        off.stop()


def test_prefix_cache_env_kill_switch(small, monkeypatch):
    monkeypatch.setenv("RAY_TPU_PREFIX_CACHE", "0")
    eng = _engine(small)                  # env decides: off
    try:
        assert eng.stats()["prefix_cache"] is False
        prompt = SHARED + [9, 9]
        eng.generate(prompt, max_new_tokens=4)
        r = eng.generate(prompt, max_new_tokens=4)
        assert eng.stats()["prefix_hit_tokens"] == 0
        assert len(r["tokens"]) == 4
    finally:
        eng.stop()


def test_full_prompt_match_forces_cow(small):
    """A prompt that is ENTIRELY cached recomputes only its last token;
    that write lands in a shared sealed block, so the engine must fork
    it (copy-on-write) — and the output must not change."""
    eng = _engine(small, prefix_cache=True)
    try:
        prompt = SHARED[:16]              # exactly 2 pages
        first = eng.generate(prompt, max_new_tokens=6)
        again = eng.generate(prompt, max_new_tokens=6)
        assert again["tokens"] == first["tokens"]
        s = eng.stats()
        assert s["cow_copies"] >= 1
        assert s["prefix_hit_tokens"] >= 16
    finally:
        eng.stop()


def test_cow_divergence_correctness(small):
    """Two prompts diverge INSIDE the last shared page: the cache may
    only reuse full matching pages, and the diverged request's pages
    must not be corrupted by sharing (greedy output matches a
    cache-off engine for both orders)."""
    on = _engine(small, prefix_cache=True)
    off = _engine(small, prefix_cache=False)
    try:
        a = SHARED[:16] + [5, 6, 7]
        b = SHARED[:16] + [5, 9, 7]       # diverges mid-page
        for p in (a, b, a, b):
            assert on.generate(p, max_new_tokens=6)["tokens"] == \
                off.generate(p, max_new_tokens=6)["tokens"], p
    finally:
        on.stop()
        off.stop()


def test_oversubscription_completes_via_preemption(small):
    """More concurrent KV demand than the pool holds: requests complete
    via preempt+recompute (no deadlock, no wrong tokens) and the
    preempt counter is nonzero."""
    # 8 usable blocks of 8 tokens; each request spans ceil(32/8)=4
    # blocks at full length -> only 2 fit fully, 4 are admitted (lazy
    # growth covers prompt + one decode window).
    eng = _engine(small, prefix_cache=False, kv_pages=9, kv_preempt=True)
    ref = _engine(small, prefix_cache=False)    # roomy reference
    try:
        prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5, i + 6]
                   for i in range(0, 40, 10)]
        futs = [eng.submit(p, max_new_tokens=26) for p in prompts]
        results = [f.result(timeout=300) for f in futs]
        assert eng.preemptions > 0
        for p, r in zip(prompts, results):
            expect = ref.generate(p, max_new_tokens=26)["tokens"]
            assert r["tokens"] == expect, p
        assert eng.completed == 4
    finally:
        eng.stop()
        ref.stop()


def test_preempt_restore_determinism(small):
    """Per-request sampling keys make preemption invisible to the
    sample stream: the same seeded temperature workload, run twice
    through a pool-starved engine (preemptions forced), produces
    identical tokens both times."""
    def run():
        eng = _engine(small, prefix_cache=True, kv_pages=9,
                      kv_preempt=True)
        try:
            prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(4)]
            # Submit everything BEFORE the engine thread runs so wave
            # composition (and hence the preemption schedule) is
            # timing-independent.
            eng.stop()                    # joins the loop thread
            futs = [eng.submit(p, max_new_tokens=26, temperature=0.8)
                    for p in prompts]
            eng.start()
            toks = [f.result(timeout=300)["tokens"] for f in futs]
            return toks, eng.preemptions
        finally:
            eng.stop()
    t1, p1 = run()
    t2, p2 = run()
    assert p1 > 0 and p2 > 0
    assert t1 == t2


def test_refcount_invariants_after_serving(small):
    """After a mixed workload quiesces, the block-state partition must
    hold and every block must be free or cached-evictable (nothing
    leaked, nothing double-freed)."""
    eng = _engine(small, prefix_cache=True)
    try:
        for i in range(5):
            eng.generate(SHARED + [60 + i], max_new_tokens=5)
        eng.generate(SHARED[:16], max_new_tokens=3)       # COW path
        mgr = eng._mgr
        mgr.check()
        assert all(s is None for s in eng._slots)
        assert mgr.free_count() + mgr.cached_count() == mgr.n_blocks
        assert mgr.evictable_count() == mgr.cached_count()
    finally:
        eng.stop()


def test_cache_eviction_under_pressure_still_correct(small):
    """Pool too small to keep every finished prefix cached: LRU leaves
    are evicted to serve new requests, and outputs stay correct."""
    eng = _engine(small, prefix_cache=True, kv_pages=7)
    off = _engine(small, prefix_cache=False)
    try:
        prompts = [[i * 3 + 1] * 10 + [i + 1, i + 2] for i in range(6)]
        for p in prompts:
            assert eng.generate(p, max_new_tokens=4)["tokens"] == \
                off.generate(p, max_new_tokens=4)["tokens"], p
        assert eng.stats()["evictions"] > 0
        eng._mgr.check()
    finally:
        eng.stop()
        off.stop()


def test_streaming_with_prefix_cache(small):
    """Token streaming composes with the prefix-cache prefill path."""
    import queue as _q

    eng = _engine(small, prefix_cache=True)
    try:
        eng.generate(SHARED + [1], max_new_tokens=4)      # populate
        q: _q.Queue = _q.Queue()
        fut = eng.submit(SHARED + [2], max_new_tokens=4, token_queue=q)
        streamed = []
        while True:
            tok = q.get(timeout=120)
            if tok is None:
                break
            streamed.append(tok)
        assert streamed == fut.result(timeout=10)["tokens"]
        assert eng.stats()["prefix_hit_tokens"] > 0
    finally:
        eng.stop()


def test_engine_metrics_exported(small):
    """Engine counters surface through utils.metrics (the dashboard
    /metrics exposition reads this registry)."""
    from ray_tpu.utils import metrics as um

    eng = _engine(small, prefix_cache=True)
    try:
        eng.generate(SHARED + [3], max_new_tokens=4)
        eng.generate(SHARED + [4], max_new_tokens=4)
        eng.stats()                       # forces a metrics flush
        with um._registry_lock:
            names = set(um._registry)
        assert {"serve_llm_prefill_tokens", "serve_llm_decode_tokens",
                "serve_llm_prefix_hit_tokens",
                "serve_llm_batch_occupancy"} <= names
        snap = um._registry["serve_llm_prefill_tokens"].snapshot()
        vals = {v["tags"]["engine"]: v["value"] for v in snap["values"]}
        assert vals.get("llm", 0) > 0
    finally:
        eng.stop()


def test_metrics_get_or_create_idempotent():
    from ray_tpu.utils import metrics as um

    a = um.get_or_create(um.Counter, "test_goc_counter", "d", ("t",))
    b = um.get_or_create(um.Counter, "test_goc_counter", "d", ("t",))
    assert a is b
    with pytest.raises(TypeError, match="already registered"):
        um.get_or_create(um.Gauge, "test_goc_counter")


def test_llmserver_shutdown_hook(small):
    """Replica teardown calls shutdown() (not GC): the engine thread
    must stop deterministically, and reconfigure must rebuild the
    engine with the old one stopped first."""
    from ray_tpu.serve.llm import LLMServer

    cfg, params = small
    server = LLMServer(cfg, params=params, max_batch=2, max_len=64,
                       page_size=8)
    t = server.engine._thread
    assert t is not None and t.is_alive()
    server.shutdown()
    assert not server.engine._thread.is_alive()
    # Rebuild path: knob change swaps the engine; old thread stays dead.
    server2 = LLMServer(cfg, params=params, max_batch=2, max_len=64,
                        page_size=8)
    old = server2.engine
    server2.reconfigure({"page_size": 16})
    assert server2.engine is not old
    assert not old._thread.is_alive()
    assert server2.engine.page == 16
    with pytest.raises(ValueError, match="engine_config"):
        server2.reconfigure({"page_sz": 16})
    # Operator-facing kv_blocks name works in user_config too (same
    # mapping as schema engine_config).
    server2.reconfigure({"kv_blocks": 12})
    assert server2.engine.n_pages == 12
    server2.shutdown()


def test_reconfigure_fails_inflight_instead_of_hanging(small):
    """Config-only reconfigure swaps engines WITHOUT a drain: requests
    the old engine still holds must fail fast, not hang forever."""
    import concurrent.futures

    from ray_tpu.serve.llm import LLMServer

    cfg, params = small
    server = LLMServer(cfg, params=params, max_batch=2, max_len=64,
                       page_size=8)
    server.engine.stop()                  # park requests in the queue
    fut = server.engine.submit([1, 2, 3], max_new_tokens=8)
    server.reconfigure({"page_size": 16})
    with pytest.raises(RuntimeError, match="rebuilt by reconfigure"):
        fut.result(timeout=10)
    # The new engine serves normally.
    r = server.engine.generate([1, 2, 3], max_new_tokens=4)
    assert len(r["tokens"]) == 4
    server.shutdown()


def test_schema_engine_config_plumbing():
    """Declarative engine_config (page_size / kv_blocks / prefix_cache)
    reaches the deployment's init kwargs; unknown keys are rejected at
    parse time."""
    from ray_tpu.serve.schema import ApplicationSchema, DeploymentSchema

    with pytest.raises(ValueError, match="engine_config"):
        DeploymentSchema.from_dict(
            {"name": "d", "engine_config": {"pages": 4}})
    app = ApplicationSchema.from_dict({
        "name": "a",
        "import_path": "tests.serve_test_app:build_echo",
        "deployments": [{
            "name": "Echo",
            "engine_config": {"page_size": 64, "kv_blocks": 32,
                              "prefix_cache": False},
        }],
    })
    target = app.load()
    node = target._walk({})[-1]
    assert node.init_kwargs["page_size"] == 64
    assert node.init_kwargs["kv_pages"] == 32       # operator name maps
    assert node.init_kwargs["prefix_cache"] is False
