"""Test fixtures.

Mirrors the reference's strategy (SURVEY §4): a shared single-node runtime
for most tests (ray: ray_start_shared fixtures), explicit multi-agent
Cluster for scheduling/fault tests, and jax pinned to an 8-device virtual
CPU platform so multi-chip sharding logic runs on one machine
(the fake-ICI analog of ray's FakeMultiNodeProvider / MockNcclGroup).
"""
import os

# Must be set before jax ever initializes: 8 virtual CPU devices stand in
# for an 8-chip slice in all sharding tests.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def ray_shared():
    """Shared local cluster (4 CPUs): initialized on first use, re-created
    if another fixture (e.g. the multi-node cluster) tore it down."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


@pytest.fixture(scope="session", autouse=True)
def _shutdown_at_end():
    yield
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
