"""Test fixtures.

Mirrors the reference's strategy (SURVEY §4): a shared single-node runtime
for most tests (ray: ray_start_shared fixtures), explicit multi-agent
Cluster for scheduling/fault tests, and jax pinned to an 8-device virtual
CPU platform so multi-chip sharding logic runs on one machine
(the fake-ICI analog of ray's FakeMultiNodeProvider / MockNcclGroup).
"""
import os

# 8 virtual CPU devices stand in for an 8-chip slice in all sharding tests.
# The env-var-at-launch route (JAX_PLATFORMS/XLA_FLAGS) does NOT work
# here: the machine's sitecustomize imports jax at interpreter startup,
# so the switch must happen post-import.  jax.config is the first
# choice; jax builds without the `jax_num_cpu_devices` option (this
# image's 0.4.x graft) take the XLA_FLAGS fallback — the CPU backend
# reads XLA_FLAGS at INITIALIZATION, which has not happened yet at
# conftest import.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402,F401 - imported before any backend init

from ray_tpu._private.config import ensure_cpu_devices  # noqa: E402
from ray_tpu._private.jax_compat import install as _jax_compat  # noqa: E402

ensure_cpu_devices(8)
_jax_compat()

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: process-killing fault-injection suites (test_chaos*, "
        "test_failpoints) — each test runs its own cluster and kills "
        "pieces of it; deselect with -m 'not chaos' for a quiet pass")


@pytest.hookimpl(wrapper=True)
def pytest_runtest_call(item):
    """Per-test watchdog (pytest-timeout isn't in this image): SIGALRM
    interrupts a wedged main-thread wait, failing THAT test with a live
    stack instead of hanging the whole suite — distributed-runtime bugs
    here historically manifest as infinite gets."""
    import signal

    budget = int(os.environ.get("RAY_TPU_TEST_TIMEOUT_S", "900"))

    def _fire(signum, frame):
        # All-thread dump first: the main-thread frame usually shows only
        # a queue/future wait — the THE interesting stack (executor
        # threads, IO loop) is elsewhere.
        import faulthandler
        import sys

        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        raise TimeoutError(
            f"watchdog: {item.nodeid} exceeded {budget}s "
            f"(frame: {frame.f_code.co_filename}:{frame.f_lineno})")

    old = signal.signal(signal.SIGALRM, _fire)
    signal.alarm(budget)
    try:
        return (yield)
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


@pytest.fixture
def ray_shared():
    """Shared local cluster (4 CPUs): initialized on first use, re-created
    if another fixture (e.g. the multi-node cluster) tore it down."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


@pytest.fixture(scope="session", autouse=True)
def _shutdown_at_end():
    yield
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
