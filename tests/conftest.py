"""Test fixtures.

Mirrors the reference's strategy (SURVEY §4): a shared single-node runtime
for most tests (ray: ray_start_shared fixtures), explicit multi-agent
Cluster for scheduling/fault tests, and jax pinned to an 8-device virtual
CPU platform so multi-chip sharding logic runs on one machine
(the fake-ICI analog of ray's FakeMultiNodeProvider / MockNcclGroup).
"""
import os

# 8 virtual CPU devices stand in for an 8-chip slice in all sharding tests.
# The env-var route (JAX_PLATFORMS/XLA_FLAGS) does NOT work here: the
# machine's sitecustomize imports jax at interpreter startup, so only
# jax.config.update takes effect.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:
    # A backend already initialized (e.g. plugin imported jax first);
    # tests then run on whatever devices exist.
    pass

import pytest  # noqa: E402


@pytest.fixture
def ray_shared():
    """Shared local cluster (4 CPUs): initialized on first use, re-created
    if another fixture (e.g. the multi-node cluster) tore it down."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


@pytest.fixture(scope="session", autouse=True)
def _shutdown_at_end():
    yield
    import ray_tpu

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
