"""Serve ingress parity: per-node proxies, gRPC ingress, declarative
config apply (reference: serve/_private/proxy.py gRPCProxy:540 +
per-node ProxyActor:1130, serve/schema.py declarative deploy).
"""
import json
import socket
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_up():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    serve.start()
    yield serve
    serve.shutdown()


def _grpc_retry_routed(call, payload, timeout_s=30.0):
    """Invoke a gRPC unary call, retrying while the app is NOT_FOUND:
    per-node proxies learn routes from a poll loop, so a just-deployed
    app is briefly unrouted (the HTTP tests get the same grace via the
    serve controller's status wait)."""
    import grpc

    deadline = time.monotonic() + timeout_s
    while True:
        try:
            return call(payload, timeout=30)
        except grpc.RpcError as e:
            if (e.code() == grpc.StatusCode.NOT_FOUND
                    and time.monotonic() < deadline):
                time.sleep(0.3)
                continue
            raise


def _http_json(port, path, payload=None, method="GET"):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type":
                                          "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode())


class TestDeclarativeConfig:
    def test_apply_and_replace(self, serve_up):
        from ray_tpu.serve.schema import apply_config

        routes = apply_config({"applications": [
            {"name": "mult", "import_path": "serve_test_app:build_app",
             "route_prefix": "/mult", "args": {"multiplier": 3}},
        ]})
        assert routes == {"mult": "/mult"}
        h = serve.get_app_handle("mult")
        assert h.remote(14).result(timeout_s=60) == 42

        # Re-apply with a different app set: old app deleted, new added.
        apply_config({"applications": [
            {"name": "echo", "import_path": "serve_test_app:build_echo",
             "route_prefix": "/echo"},
        ]})
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = serve.status()
            if "mult" not in st and "echo" in st:
                break
            time.sleep(0.2)
        st = serve.status()
        assert "mult" not in st and "echo" in st, st
        h2 = serve.get_app_handle("echo")
        assert h2.remote("hi").result(timeout_s=60) == {"echo": "hi"}
        serve.delete("echo")

    def test_deployment_overrides(self, serve_up):
        from ray_tpu.serve.schema import ApplicationSchema

        app = ApplicationSchema.from_dict(
            {"name": "m", "import_path": "serve_test_app:build_app",
             "deployments": [{"name": "Mult", "num_replicas": 2,
                              "max_ongoing_requests": 16}]}).load()
        d = app.deployment
        assert d.config.num_replicas == 2
        assert d.config.max_ongoing_requests == 16

    def test_unknown_keys_rejected(self, serve_up):
        from ray_tpu.serve.schema import DeploySchema

        with pytest.raises(ValueError, match="unknown application"):
            DeploySchema.from_dict({"applications": [
                {"name": "x", "import_path": "a:b", "bogus": 1}]})


class TestGRPCIngress:
    def test_predict_and_streaming(self, serve_up):
        import grpc

        @serve.deployment
        class G:
            def __call__(self, x):
                return {"doubled": x * 2}

            def stream(self, n):
                for i in range(int(n)):
                    yield i * 10

        serve.run(G.bind(), name="gapp", route_prefix="/gapp")
        port = serve.grpc_port()
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        predict = chan.unary_unary(
            "/ray.serve.RayTpuServe/Predict",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        out = json.loads(_grpc_retry_routed(predict, json.dumps(
            {"application": "gapp", "payload": 21}).encode()))
        assert out == {"result": {"doubled": 42}}

        lister = chan.unary_unary(
            "/ray.serve.RayTpuServe/ListApplications",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        apps = json.loads(lister(b"{}", timeout=30))
        assert "gapp" in apps["applications"]

        streamer = chan.unary_stream(
            "/ray.serve.RayTpuServe/PredictStreaming",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        items = [json.loads(m)["result"] for m in streamer(
            json.dumps({"application": "gapp", "method": "stream",
                        "payload": 3}).encode(), timeout=60)]
        assert items == [0, 10, 20]
        chan.close()
        serve.delete("gapp")

    def test_error_paths_clean_status(self, serve_up):
        """Error branches must surface as gRPC statuses.  Regression:
        grpc.aio's context.abort is a coroutine — an unawaited abort was
        a silent no-op and errors fell through to an UnboundLocalError
        (StatusCode.UNKNOWN) instead of the intended status."""
        import grpc
        import pytest

        @serve.deployment
        class Erring:
            def __call__(self, x):
                raise ValueError("bad payload")

        serve.run(Erring.bind(), name="errapp", route_prefix="/errapp")
        port = serve.grpc_port()
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        predict = chan.unary_unary(
            "/ray.serve.RayTpuServe/Predict",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)

        with pytest.raises(grpc.RpcError) as ei:
            predict(json.dumps({"application": "nope"}).encode(),
                    timeout=30)
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND

        with pytest.raises(grpc.RpcError) as ei:
            predict(b"not json", timeout=30)
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT

        with pytest.raises(grpc.RpcError) as ei:
            _grpc_retry_routed(predict, json.dumps(
                {"application": "errapp", "payload": 1}).encode())
        assert ei.value.code() == grpc.StatusCode.INTERNAL
        assert "ValueError" in ei.value.details()
        chan.close()
        serve.delete("errapp")
