"""Cluster launcher (`ray-tpu up/down`) + graceful node drain.

Mirrors ray: scripts.py `ray up/down/drain-node` (commands at the bottom
of /root/reference/python/ray/scripts/scripts.py) — here the YAML config
drives the existing provider surface, tested against the same fake GCE
TPU API the autoscaler-v2 suite uses.
"""
import http.server
import json
import subprocess
import sys
import threading

import pytest
import yaml

import ray_tpu
from test_autoscaler_v2 import _FakeTPUAPI  # rootdir-relative (no pkg)


@pytest.fixture
def fake_tpu_api():
    _FakeTPUAPI.nodes = {}
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeTPUAPI)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def _write_config(tmp_path, endpoint) -> str:
    cfg = {
        "cluster_name": "lc-test",
        "max_workers": 3,
        "provider": {"type": "gce_tpu", "project": "proj",
                     "zone": "us-central2-b", "api_endpoint": endpoint,
                     "metadata_endpoint": endpoint},
        "auth": {"ssh_user": "tpuuser", "ssh_private_key": "/k.pem"},
        "head_node": {"node_config": {"accelerator_type": "v5litepod-8"}},
        "worker_nodes": {"count": 2,
                         "node_config": {"accelerator_type":
                                         "v5litepod-8"}},
    }
    path = tmp_path / "cluster.yaml"
    path.write_text(yaml.safe_dump(cfg))
    return str(path)


def test_up_down_against_fake_gce(fake_tpu_api, tmp_path):
    from ray_tpu.autoscaler import launcher

    cfg = launcher.load_config(_write_config(tmp_path, fake_tpu_api))
    dry = launcher.up(cfg, dry_run=True)
    assert dry["dry_run"] and dry["would_create"]["workers"] == 2

    summary = launcher.up(cfg)
    assert len(summary["created"]) == 3        # head + 2 workers
    assert len(summary["nodes"]) == 3
    # Idempotent: a second `up` tops up nothing.
    again = launcher.up(cfg)
    assert again["created"] == []
    assert len(again["nodes"]) == 3

    downed = launcher.down(cfg)
    assert len(downed["terminated"]) == 3
    assert launcher.make_provider(cfg).non_terminated_nodes() == []


def test_attach_exec_submit_commands(fake_tpu_api, tmp_path):
    """`ray-tpu attach/exec/submit/get-head-ip` build the right ssh
    argvs against the labelled head (ray: scripts.py attach/exec/submit
    via commands.py; auth block = the reference's YAML ssh fields)."""
    from ray_tpu.autoscaler import launcher

    cfg = launcher.load_config(_write_config(tmp_path, fake_tpu_api))
    launcher.up(cfg)
    ip = launcher.get_head_ip(cfg)
    assert ip.startswith("10.0.0.")

    at = launcher.attach_command(cfg)
    assert at[0] == "ssh" and at[-1] == f"tpuuser@{ip}" and "-i" in at
    assert at[at.index("-i") + 1] == "/k.pem"

    ex = launcher.exec_command(cfg, "ray-tpu status")
    assert ex[-2] == f"tpuuser@{ip}" and ex[-1] == "ray-tpu status"

    scp, run = launcher.submit_commands(cfg, "/tmp/job.py", ["--n", "2"])
    assert scp[0] == "scp" and scp[-1] == f"tpuuser@{ip}:/tmp/job.py"
    assert run[-1].endswith("/tmp/job.py --n 2")
    launcher.down(cfg)


def test_cli_ssh_front_door_dry_run(fake_tpu_api, tmp_path):
    path = _write_config(tmp_path, fake_tpu_api)
    subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "up", path],
        capture_output=True, text=True, timeout=60, check=True)

    def cli(*args):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", *args],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr[-1000:]
        return out.stdout

    argv = json.loads(cli("exec", path, "hostname", "--dry-run"))["argv"]
    assert argv[0] == "ssh" and argv[-1] == "hostname"

    argv = json.loads(cli("attach", path, "--dry-run"))["argv"]
    assert argv[0] == "ssh" and "-tt" in argv

    # Dash-prefixed script args must pass through to the script.
    scp, run = json.loads(cli("submit", path, "--dry-run",
                              "job.py", "--n", "2"))["argvs"]
    assert scp[0] == "scp"
    assert run[-1].endswith("/tmp/job.py --n 2")

    ip = cli("get-head-ip", path).strip()
    assert ip.startswith("10.0.0.")


def test_head_recreated_after_preemption(fake_tpu_api, tmp_path):
    """A dead head with live labelled workers: head_node() is None (no
    silent worker fallback) and `up` recreates exactly one head."""
    from ray_tpu.autoscaler import launcher

    cfg = launcher.load_config(_write_config(tmp_path, fake_tpu_api))
    launcher.up(cfg)
    provider = launcher.make_provider(cfg)
    head = provider.head_node()
    provider.terminate_node(head)       # "preempted"
    assert provider.head_node() is None
    with pytest.raises(RuntimeError, match="no live head"):
        launcher.get_head_ip(cfg)
    again = launcher.up(cfg)
    assert len(again["created"]) == 1
    new_head = provider.head_node()
    assert new_head is not None and new_head != head
    launcher.down(cfg)


def test_cli_up_down(fake_tpu_api, tmp_path):
    path = _write_config(tmp_path, fake_tpu_api)
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli", "up", path,
         "--dry-run"], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-1000:]
    assert json.loads(out.stdout)["would_create"]["workers"] == 2


def test_drain_node_graceful():
    from ray_tpu.cluster_utils import Cluster

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2, "drainme": 1})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(num_cpus=0.1, resources={"drainme": 0.1})
        class OnTarget:
            def ping(self):
                return ray_tpu.get_runtime_context().node_id

        @ray_tpu.remote(num_cpus=0.1)
        def where():
            return ray_tpu.get_runtime_context().node_id

        a = OnTarget.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=60) == n2["node_id"]

        from ray_tpu._private.worker import global_worker

        core = global_worker()
        reply, _ = core.call(core.controller_addr, "drain_node",
                             {"node_id": n2["node_id"]}, timeout=30.0)
        assert reply["ok"] and reply["state"] == "DRAINING"

        # New work avoids the draining node...
        nodes = set(ray_tpu.get([where.remote() for _ in range(8)],
                                timeout=60))
        assert n2["node_id"] not in nodes
        # ...but running work keeps serving, and the node is NOT dead.
        assert ray_tpu.get(a.ping.remote(), timeout=60) == n2["node_id"]
        import time
        time.sleep(3)   # several heartbeat periods
        assert ray_tpu.get(a.ping.remote(), timeout=60) == n2["node_id"]
        ray_tpu.kill(a)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
