"""Cluster tooling tests: state API, metrics, jobs, workflow, runtime envs,
autoscaler.

Mirrors ray: python/ray/tests/test_state_api*.py, test_metrics_agent.py,
dashboard/modules/job/tests, workflow tests, test_runtime_env*.py, and the
FakeMultiNodeProvider-based autoscaler tests (SURVEY §4).
"""
import json
import os
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


def test_state_api(rt):
    from ray_tpu.utils import state

    @ray_tpu.remote
    class Probe:
        def ping(self):
            return 1

    @ray_tpu.remote
    def a_task():
        return 1

    p = Probe.remote()
    ray_tpu.get(p.ping.remote())
    ray_tpu.get(a_task.remote())
    nodes = state.list_nodes()
    assert nodes and nodes[0]["state"] == "ALIVE"
    actors = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert any(a["class_name"] == "Probe" for a in actors)
    # task events flush on a period (ray: TaskEventBuffer push interval)
    deadline = time.monotonic() + 10
    tasks = []
    while time.monotonic() < deadline and not tasks:
        tasks = state.list_tasks()
        time.sleep(0.3)
    assert tasks
    summary = state.summarize_tasks()
    assert summary["cluster"]["total_tasks"] >= 1
    ray_tpu.kill(p)


def test_metrics(rt):
    from ray_tpu.utils import metrics as m
    from ray_tpu.utils import state

    c = m.Counter("test_requests", "reqs", tag_keys=("route",))
    c.inc(2, tags={"route": "/a"})
    c.inc(1, tags={"route": "/b"})
    g = m.Gauge("test_inflight")
    g.set(7)
    h = m.Histogram("test_latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    snap = c.snapshot()
    assert {v["value"] for v in snap["values"]} == {2.0, 1.0}
    # flushed to the controller and visible via the state API
    deadline = time.monotonic() + 3 * m.FLUSH_PERIOD_S
    found = False
    while time.monotonic() < deadline and not found:
        for worker_snap in state.list_metrics():
            names = {s["name"] for s in worker_snap["metrics"]}
            if {"test_requests", "test_inflight"} <= names:
                found = True
        time.sleep(0.3)
    assert found, "metrics never reached the controller KV"


def test_job_submission(rt):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    jid = client.submit_job(
        entrypoint="python -c \"print('job says hi')\"",
        metadata={"owner": "test"})
    status = client.wait_until_finished(jid, timeout_s=60)
    assert status == "SUCCEEDED"
    assert "job says hi" in client.get_job_logs(jid)
    jobs = client.list_jobs()
    assert any(j["job_id"] == jid for j in jobs)


def test_job_failure_status(rt):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    jid = client.submit_job(entrypoint="python -c 'import sys; sys.exit(3)'")
    assert client.wait_until_finished(jid, timeout_s=60) == "FAILED"
    assert client.get_job_info(jid)["return_code"] == 3


def test_workflow_run_and_resume(rt, tmp_path):
    from ray_tpu import workflow

    calls = {"n": 0}

    @ray_tpu.remote
    def flaky(x):
        return x + 1

    @ray_tpu.remote
    def double(x):
        return x * 2

    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = double.bind(flaky.bind(inp))

    storage = str(tmp_path / "wf")
    out = workflow.run(dag, 5, workflow_id="wf1", storage=storage)
    assert out == 12
    assert workflow.get_status("wf1", storage=storage) == "SUCCEEDED"
    assert workflow.get_output("wf1", storage=storage) == 12
    # resume of a finished workflow replays from checkpoints
    assert workflow.resume("wf1", storage=storage) == 12
    assert ("wf1", "SUCCEEDED") in workflow.list_all(storage=storage)
    workflow.delete("wf1", storage=storage)
    assert workflow.get_status("wf1", storage=storage) == "NOT_FOUND"


def test_workflow_step_checkpoint_skips_done(rt, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = tmp_path / "ran_count"
    marker.write_text("0")

    @ray_tpu.remote
    def counted(x, marker_path):
        n = int(open(marker_path).read()) + 1
        open(marker_path, "w").write(str(n))
        return x + n

    with InputNode() as inp:
        dag = counted.bind(inp, str(marker))

    storage = str(tmp_path / "wf")
    out1 = workflow.run(dag, 10, workflow_id="wf2", storage=storage)
    out2 = workflow.resume("wf2", storage=storage)
    assert out1 == out2 == 11
    assert marker.read_text() == "1"   # step executed exactly once


def test_runtime_env_env_vars(rt):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("RAY_TPU_TEST_FLAG", "missing")

    ref = read_env.options(
        runtime_env={"env_vars": {"RAY_TPU_TEST_FLAG": "on"}}).remote()
    assert ray_tpu.get(ref) == "on"
    # and without the env, the variable must not leak from the pooled worker
    assert ray_tpu.get(read_env.remote()) == "missing"


def test_runtime_env_working_dir(rt, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymod_rt_env.py").write_text("VALUE = 'from-working-dir'\n")

    @ray_tpu.remote
    def use_module():
        import mymod_rt_env

        return mymod_rt_env.VALUE

    ref = use_module.options(
        runtime_env={"working_dir": str(pkg)}).remote()
    assert ray_tpu.get(ref) == "from-working-dir"


def test_cli_status_and_list(rt):
    """Smoke the CLI code paths in-process (full subprocess CLI covered by
    job submission)."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.scripts import cli

    class A:
        address = global_worker().controller_addr

    # _require_address picks up explicit address
    assert cli._require_address(A) == A.address


def test_cli_status_and_memory(rt):
    """`ray-tpu status` and `ray-tpu memory` against a live cluster
    (ray: `ray status` / `ray memory` CLI)."""
    import subprocess
    import sys

    from ray_tpu._private.worker import global_worker

    addr = global_worker().controller_addr
    for cmd, expect in (("status", "node(s)"), ("memory", "cluster:")):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", cmd,
             "--address", addr],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr[-1000:]
        assert expect in out.stdout, out.stdout
