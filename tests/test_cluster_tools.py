"""Cluster tooling tests: state API, metrics, jobs, workflow, runtime envs,
autoscaler.

Mirrors ray: python/ray/tests/test_state_api*.py, test_metrics_agent.py,
dashboard/modules/job/tests, workflow tests, test_runtime_env*.py, and the
FakeMultiNodeProvider-based autoscaler tests (SURVEY §4).
"""
import json
import os
import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def rt():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    yield ray_tpu


def test_state_api(rt):
    from ray_tpu.utils import state

    @ray_tpu.remote
    class Probe:
        def ping(self):
            return 1

    @ray_tpu.remote
    def a_task():
        return 1

    p = Probe.remote()
    ray_tpu.get(p.ping.remote())
    ray_tpu.get(a_task.remote())
    nodes = state.list_nodes()
    assert nodes and nodes[0]["state"] == "ALIVE"
    actors = state.list_actors(filters=[("state", "=", "ALIVE")])
    assert any(a["class_name"] == "Probe" for a in actors)
    # task events flush on a period (ray: TaskEventBuffer push interval)
    deadline = time.monotonic() + 10
    tasks = []
    while time.monotonic() < deadline and not tasks:
        tasks = state.list_tasks()
        time.sleep(0.3)
    assert tasks
    summary = state.summarize_tasks()
    assert summary["cluster"]["total_tasks"] >= 1
    ray_tpu.kill(p)


def test_metrics(rt):
    from ray_tpu.utils import metrics as m
    from ray_tpu.utils import state

    c = m.Counter("test_requests", "reqs", tag_keys=("route",))
    c.inc(2, tags={"route": "/a"})
    c.inc(1, tags={"route": "/b"})
    g = m.Gauge("test_inflight")
    g.set(7)
    h = m.Histogram("test_latency", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(5.0)
    snap = c.snapshot()
    assert {v["value"] for v in snap["values"]} == {2.0, 1.0}
    # flushed to the controller and visible via the state API
    deadline = time.monotonic() + 3 * m.FLUSH_PERIOD_S
    found = False
    while time.monotonic() < deadline and not found:
        for worker_snap in state.list_metrics():
            names = {s["name"] for s in worker_snap["metrics"]}
            if {"test_requests", "test_inflight"} <= names:
                found = True
        time.sleep(0.3)
    assert found, "metrics never reached the controller KV"


def test_job_submission(rt):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    jid = client.submit_job(
        entrypoint="python -c \"print('job says hi')\"",
        metadata={"owner": "test"})
    status = client.wait_until_finished(jid, timeout_s=60)
    assert status == "SUCCEEDED"
    assert "job says hi" in client.get_job_logs(jid)
    jobs = client.list_jobs()
    assert any(j["job_id"] == jid for j in jobs)


def test_job_failure_status(rt):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    jid = client.submit_job(entrypoint="python -c 'import sys; sys.exit(3)'")
    assert client.wait_until_finished(jid, timeout_s=60) == "FAILED"
    assert client.get_job_info(jid)["return_code"] == 3


def test_workflow_run_and_resume(rt, tmp_path):
    from ray_tpu import workflow

    calls = {"n": 0}

    @ray_tpu.remote
    def flaky(x):
        return x + 1

    @ray_tpu.remote
    def double(x):
        return x * 2

    from ray_tpu.dag import InputNode

    with InputNode() as inp:
        dag = double.bind(flaky.bind(inp))

    storage = str(tmp_path / "wf")
    out = workflow.run(dag, 5, workflow_id="wf1", storage=storage)
    assert out == 12
    assert workflow.get_status("wf1", storage=storage) == "SUCCEEDED"
    assert workflow.get_output("wf1", storage=storage) == 12
    # resume of a finished workflow replays from checkpoints
    assert workflow.resume("wf1", storage=storage) == 12
    assert ("wf1", "SUCCEEDED") in workflow.list_all(storage=storage)
    workflow.delete("wf1", storage=storage)
    assert workflow.get_status("wf1", storage=storage) == "NOT_FOUND"


def test_workflow_step_checkpoint_skips_done(rt, tmp_path):
    from ray_tpu import workflow
    from ray_tpu.dag import InputNode

    marker = tmp_path / "ran_count"
    marker.write_text("0")

    @ray_tpu.remote
    def counted(x, marker_path):
        n = int(open(marker_path).read()) + 1
        open(marker_path, "w").write(str(n))
        return x + n

    with InputNode() as inp:
        dag = counted.bind(inp, str(marker))

    storage = str(tmp_path / "wf")
    out1 = workflow.run(dag, 10, workflow_id="wf2", storage=storage)
    out2 = workflow.resume("wf2", storage=storage)
    assert out1 == out2 == 11
    assert marker.read_text() == "1"   # step executed exactly once


def test_runtime_env_env_vars(rt):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("RAY_TPU_TEST_FLAG", "missing")

    ref = read_env.options(
        runtime_env={"env_vars": {"RAY_TPU_TEST_FLAG": "on"}}).remote()
    assert ray_tpu.get(ref) == "on"
    # and without the env, the variable must not leak from the pooled worker
    assert ray_tpu.get(read_env.remote()) == "missing"


def test_runtime_env_working_dir(rt, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "mymod_rt_env.py").write_text("VALUE = 'from-working-dir'\n")

    @ray_tpu.remote
    def use_module():
        import mymod_rt_env

        return mymod_rt_env.VALUE

    ref = use_module.options(
        runtime_env={"working_dir": str(pkg)}).remote()
    assert ray_tpu.get(ref) == "from-working-dir"


def _make_wheel(wheel_dir, name: str, version: str, source: str) -> None:
    """Hand-roll a minimal pure-python wheel (no build backend needed —
    a wheel is a zip with dist-info metadata)."""
    import zipfile

    tag = f"{name}-{version}"
    whl = wheel_dir / f"{tag}-py3-none-any.whl"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py", source)
        zf.writestr(f"{tag}.dist-info/METADATA",
                    f"Metadata-Version: 2.1\nName: {name}\n"
                    f"Version: {version}\n")
        zf.writestr(f"{tag}.dist-info/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: test\n"
                    "Root-Is-Purelib: true\nTag: py3-none-any\n")
        zf.writestr(f"{tag}.dist-info/RECORD", "")


def test_runtime_env_pip_offline(rt, tmp_path):
    """pip runtime env from a local wheel dir (ray: runtime_env/pip.py
    minus the network): the env's task imports the package; a plain task
    on the same pooled worker must NOT see it."""
    wheel_dir = tmp_path / "wheels"
    wheel_dir.mkdir()
    _make_wheel(wheel_dir, "envtestpkg", "1.0", "VALUE = 42\n")

    @ray_tpu.remote
    def with_pkg():
        import envtestpkg

        return envtestpkg.VALUE

    @ray_tpu.remote
    def without_pkg():
        try:
            import envtestpkg  # noqa: F401

            return "leaked"
        except ImportError:
            return "isolated"

    env = {"pip": {"packages": ["envtestpkg"],
                   "wheel_dir": str(wheel_dir)}}
    assert ray_tpu.get(with_pkg.options(runtime_env=env).remote()) == 42
    assert ray_tpu.get(without_pkg.remote()) == "isolated"
    # Version pinning resolves from the same local dir.
    _make_wheel(wheel_dir, "envtestpkg", "2.0", "VALUE = 43\n")
    env2 = {"pip": {"packages": ["envtestpkg==2.0"],
                    "wheel_dir": str(wheel_dir)}}
    assert ray_tpu.get(with_pkg.options(runtime_env=env2).remote()) == 43


def test_runtime_env_venv_isolated_interpreter(rt, tmp_path):
    """venv runtime env = a DEDICATED worker on an isolated interpreter
    (the conda analog; ray: runtime_env/conda.py + the env-keyed
    WorkerPool).  The env's tasks run under the venv prefix with its
    offline-installed package; plain workers never see either."""
    import sys

    wheel_dir = tmp_path / "wheels"
    wheel_dir.mkdir()
    _make_wheel(wheel_dir, "venvonlypkg", "1.0", "VALUE = 7\n")

    @ray_tpu.remote
    def probe():
        import venvonlypkg

        return sys.prefix, venvonlypkg.VALUE

    @ray_tpu.remote
    def plain():
        try:
            import venvonlypkg  # noqa: F401

            return "leaked"
        except ImportError:
            return sys.prefix

    env = {"venv": {"packages": ["venvonlypkg"],
                    "wheel_dir": str(wheel_dir)}}
    prefix, val = ray_tpu.get(
        probe.options(runtime_env=env).remote(), timeout=180)
    assert val == 7
    assert "/venv/" in prefix and prefix != sys.prefix
    assert ray_tpu.get(plain.remote(), timeout=60) != prefix

    # Same env hash reuses the same dedicated worker (keyed pool);
    # actors route through the venv path too.
    @ray_tpu.remote
    class EnvActor:
        def where(self):
            return sys.prefix

    a = EnvActor.options(runtime_env=env).remote()
    assert ray_tpu.get(a.where.remote(), timeout=180) == prefix
    ray_tpu.kill(a)


def test_lease_park_is_bounded_and_node_recovers():
    """A lease request that can't be satisfied parks agent-side for at
    most `lease_park_s`, then gets an explicit {"retry": True} reply.
    Before the fix the agent parked forever: the client timed out, and
    when capacity freed the agent granted a lease into a future nobody
    read — a worker leased-to-nobody that the dead-submitter probe never
    reaps (the submitter is alive), wedging the node one worker at a
    time (suite post-mortem: every later lease request timed out while
    all worker processes sat idle)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 1},
                 _system_config={"lease_park_s": 0.3,
                                 "max_workers_per_node": 1,
                                 "prestart_workers": 1})
    try:
        from ray_tpu._private.worker import global_worker

        @ray_tpu.remote(num_cpus=1)
        def hold(sec):
            time.sleep(sec)
            return 1

        @ray_tpu.remote(num_cpus=1)
        def quick():
            return 2

        core = global_worker()
        r = hold.remote(6.0)
        # Probe with raw lease requests until one finds the CPU taken:
        # that one must come back {"retry": True} (bounded park), never
        # hang to the RPC timeout.
        deadline = time.monotonic() + 30
        while True:
            reply, _ = core.call(
                core.agent_addr, "request_lease",
                {"resources": {"CPU": 1.0}, "submitter": core.address},
                timeout=10.0)
            if reply.get("retry"):
                break
            if reply.get("granted"):
                # Raced ahead of hold's own lease: give it back.
                core.call(core.agent_addr, "return_lease",
                          {"lease_id": reply["lease_id"]}, timeout=5.0)
            assert time.monotonic() < deadline, f"no retry reply: {reply}"
            time.sleep(0.2)
        # The node is NOT wedged: the held task finishes and fresh work
        # still schedules onto the single worker (a leaked zombie lease
        # would hold both the CPU and the only worker slot forever).
        assert ray_tpu.get(r, timeout=60) == 1
        assert ray_tpu.get(quick.remote(), timeout=60) == 2
    finally:
        ray_tpu.shutdown()
        ray_tpu.init(resources={"CPU": 4})


def test_venv_lease_evicts_idle_worker_at_cap(tmp_path):
    """Keyed pools must not deadlock at the worker cap: with the pool
    full of idle PLAIN workers, a venv lease evicts one and completes
    (before the fix it pended forever — nothing returns a lease when
    everyone is idle)."""
    import sys

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    wheel_dir = tmp_path / "wheels"
    wheel_dir.mkdir()
    _make_wheel(wheel_dir, "capevictpkg", "1.0", "VALUE = 1\n")
    ray_tpu.init(resources={"CPU": 4},
                 _system_config={"max_workers_per_node": 1})
    try:
        @ray_tpu.remote
        def plain():
            return sys.prefix

        @ray_tpu.remote
        def in_venv():
            import capevictpkg

            return sys.prefix, capevictpkg.VALUE

        plain_prefix = ray_tpu.get(plain.remote(), timeout=60)
        env = {"venv": {"packages": ["capevictpkg"],
                        "wheel_dir": str(wheel_dir)}}
        prefix, val = ray_tpu.get(
            in_venv.options(runtime_env=env).remote(), timeout=180)
        assert val == 1 and prefix != plain_prefix
        # ...and back: a plain task evicts the idle venv worker.
        assert ray_tpu.get(plain.remote(), timeout=60) == plain_prefix
    finally:
        ray_tpu.shutdown()
        # Restore the module-shared runtime (the module-scoped `rt`
        # fixture only inits on first use; later tests expect it live).
        ray_tpu.init(resources={"CPU": 4})


def test_venv_rejected_for_tpu_tasks(rt):
    @ray_tpu.remote
    def f():
        return 1

    with pytest.raises(ValueError, match="unsupported for TPU"):
        f.options(num_tpus=1, runtime_env={"venv": True}).remote()


def test_cli_status_and_list(rt):
    """Smoke the CLI code paths in-process (full subprocess CLI covered by
    job submission)."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.scripts import cli

    class A:
        address = global_worker().controller_addr

    # _require_address picks up explicit address
    assert cli._require_address(A) == A.address


def test_cli_status_and_memory(rt):
    """`ray-tpu status` and `ray-tpu memory` against a live cluster
    (ray: `ray status` / `ray memory` CLI)."""
    import subprocess
    import sys

    from ray_tpu._private.worker import global_worker

    addr = global_worker().controller_addr
    for cmd, expect in (("status", "node(s)"), ("memory", "cluster:")):
        out = subprocess.run(
            [sys.executable, "-m", "ray_tpu.scripts.cli", cmd,
             "--address", addr],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr[-1000:]
        assert expect in out.stdout, out.stdout


def test_workflow_retries_timeout_events(rt, tmp_path):
    """Workflow hardening (ray: workflow_executor.py): per-step retries
    with a durable event stream, step timeouts, and bounded concurrency."""
    from ray_tpu import workflow
    from ray_tpu.dag.dag_node import InputNode

    storage = str(tmp_path / "wf")
    flaky_marker = tmp_path / "flaky"
    flaky_marker.write_text("0")

    @ray_tpu.remote
    def flaky(x, marker):
        n = int(open(marker).read()) + 1
        open(marker, "w").write(str(n))
        if n < 3:
            raise RuntimeError(f"attempt {n} fails")
        return x + 100

    with InputNode() as inp:
        dag = flaky.bind(inp, str(flaky_marker))

    events = []
    out = workflow.run(dag, 1, workflow_id="wf-retry", storage=storage,
                       step_max_retries=3, on_event=events.append)
    assert out == 101
    kinds = [e["event"] for e in events]
    assert kinds.count("failed") == 2 and kinds.count("retry") == 2
    assert kinds[-1] == "completed"
    # The durable stream matches what the listener saw.
    stored = workflow.list_events("wf-retry", storage=storage)
    assert [e["event"] for e in stored] == kinds

    # Step timeout surfaces as TimeoutError after exhausting retries.
    @ray_tpu.remote
    def sleepy():
        import time as _t

        _t.sleep(30)
        return "late"

    with InputNode() as inp2:
        dag2 = sleepy.bind()

    with pytest.raises((TimeoutError, Exception)):
        workflow.run(dag2, workflow_id="wf-timeout", storage=storage,
                     step_timeout_s=1.0)


def test_workflow_concurrency_limit(rt, tmp_path):
    """max_concurrent_steps bounds in-flight steps: with limit 1, step
    wall-clocks never overlap."""
    import json as _json

    from ray_tpu import workflow
    from ray_tpu.dag.dag_node import InputNode, MultiOutputNode

    storage = str(tmp_path / "wf")
    log = tmp_path / "spans.jsonl"

    @ray_tpu.remote
    def span(i, path):
        import time as _t

        t0 = _t.time()
        _t.sleep(0.3)
        with open(path, "a") as f:
            f.write(_json.dumps([t0, _t.time()]) + "\n")
        return i

    with InputNode() as inp:
        dag = MultiOutputNode([span.bind(i, str(log)) for i in range(3)])

    out = workflow.run(dag, None, workflow_id="wf-conc", storage=storage,
                       max_concurrent_steps=1)
    assert sorted(out) == [0, 1, 2]
    spans = sorted(_json.loads(x) for x in log.read_text().splitlines())
    for (s0, e0), (s1, _e1) in zip(spans, spans[1:]):
        assert s1 >= e0 - 0.05, f"steps overlapped: {spans}"


def test_runtime_env_custom_plugin(rt):
    """The plugin seam (ray: runtime_env/plugin.py RuntimeEnvPlugin):
    a user-defined kind ships BY VALUE in the descriptor — prepare on
    the driver, fetch+activate/deactivate around execution on a pooled
    worker, no worker-side registration."""
    from ray_tpu.runtime_env import RuntimeEnvPlugin

    class StampPlugin(RuntimeEnvPlugin):
        name = "stamp"
        priority = 3

        def __init__(self, tag):
            self.tag = tag

        def prepare(self, value, core):
            return {"tag": self.tag, "prepared": True}

        def fetch(self, wire, core):
            # Worker-side build step: write a marker file once.
            import tempfile
            self._path = tempfile.gettempdir() + f"/rt_stamp_{wire['tag']}"
            with open(self._path, "w") as f:
                f.write("built")

        def activate(self, wire, core, ctx):
            import os
            ctx["old"] = os.environ.get("RAY_TPU_STAMP")
            os.environ["RAY_TPU_STAMP"] = wire["tag"]

        def deactivate(self, wire, core, ctx):
            import os
            if ctx.get("old") is None:
                os.environ.pop("RAY_TPU_STAMP", None)
            else:
                os.environ["RAY_TPU_STAMP"] = ctx["old"]

    @ray_tpu.remote
    def read_stamp():
        import os
        return os.environ.get("RAY_TPU_STAMP")

    out = ray_tpu.get(read_stamp.options(
        runtime_env={"plugins": [StampPlugin("alpha")]}).remote(),
        timeout=120)
    assert out == "alpha"
    # Deactivation: the next task in the pooled worker sees a clean env.
    assert ray_tpu.get(read_stamp.remote(), timeout=120) is None


def test_workflow_api_extras(rt, tmp_path):
    """Round-4 workflow parity: continuation, sleep, wait_for_event,
    metadata, resume_all, cancellation error (ray: workflow/__init__)."""
    import time as _time

    from ray_tpu import workflow

    storage = str(tmp_path / "wfx")

    # Dynamic continuation: a step returns continuation(sub-dag).
    @ray_tpu.remote
    def fib(n):
        if n <= 1:
            return n
        return workflow.continuation(fib_sum.bind(n))

    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def fib_sum(n):
        return workflow.continuation(add.bind(fib.bind(n - 1),
                                              fib.bind(n - 2)))

    out = workflow.run(fib.bind(6), workflow_id="wfib",
                       storage=storage)
    assert out == 8
    # Replay: the entire continuation tree comes from checkpoints.
    assert workflow.resume("wfib", storage=storage) == 8

    # sleep is a durable step: replay is instant.
    t0 = _time.monotonic()
    workflow.run(workflow.sleep(1.0), workflow_id="wsleep",
                 storage=storage)
    took_first = _time.monotonic() - t0
    assert took_first >= 1.0
    t0 = _time.monotonic()
    assert workflow.resume("wsleep", storage=storage) == 1.0
    assert _time.monotonic() - t0 < max(1.0, took_first / 2)

    # wait_for_event completes when the listener's poll returns.
    marker = tmp_path / "event-armed"

    class FileEvent(workflow.EventListener):
        def poll_for_event(self, path):
            import os as _os
            import time as _t

            while not _os.path.exists(path):
                _t.sleep(0.05)
            return "armed"

    import threading

    threading.Timer(0.5, lambda: marker.write_text("x")).start()
    out = workflow.run(
        workflow.wait_for_event(FileEvent, str(marker)),
        workflow_id="wevent", storage=storage)
    assert out == "armed"

    # metadata + resume_all + cancellation error.
    meta = workflow.get_metadata("wsleep", storage=storage)
    assert meta["status"] == "SUCCEEDED"
    assert meta["steps"]
    assert workflow.resume_all(storage=storage) == []
    workflow.cancel("wevent", storage=storage)
    assert workflow.get_status("wevent", storage=storage) == "CANCELED"
    # A cancelled workflow's completed output is still readable; a
    # cancelled one WITHOUT output raises the typed error.
    workflow.run(workflow.sleep(0.0), workflow_id="wc2", storage=storage)
    workflow.cancel("wc2", storage=storage)
    import os as _os
    import shutil as _shutil

    _shutil.rmtree(_os.path.join(storage, "wc2", "steps"))
    with pytest.raises(workflow.WorkflowCancellationError):
        workflow.get_output("wc2", storage=storage)
