"""Serve tests: deploy, route, compose, reconfigure, batch, autoscale, HTTP.

Mirrors the reference's serve test strategy (ray: python/ray/serve/tests/,
unit subset mocks; integration against one-node ray.init — SURVEY §4).
"""
import json
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})
    serve.start()
    yield serve
    serve.shutdown()


def test_function_deployment(serve_instance):
    @serve.deployment
    def double(x):
        return x * 2

    h = serve.run(double.bind(), name="fn_app", route_prefix="/double")
    assert h.remote(21).result(timeout_s=30) == 42
    serve.delete("fn_app")


def test_class_deployment_and_composition(serve_instance):
    @serve.deployment
    class Adder:
        def __init__(self, offset):
            self.offset = offset

        def __call__(self, x):
            return x + self.offset

    @serve.deployment
    class Ingress:
        def __init__(self, adder):
            self.adder = adder

        async def __call__(self, x):
            partial = await self.adder.remote(x)
            return partial * 10

    app = Ingress.bind(Adder.bind(5))
    h = serve.run(app, name="compose", route_prefix="/compose")
    assert h.remote(1).result(timeout_s=30) == 60
    # status reflects both deployments
    st = serve.status()["compose"]
    assert st["status"] == "RUNNING"
    assert set(st["deployments"]) == {"Adder", "Ingress"}
    serve.delete("compose")


def test_multi_replica_load_balancing(serve_instance):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class WhoAmI:
        def __call__(self, _x):
            import os

            time.sleep(0.05)
            return os.getpid()

    h = serve.run(WhoAmI.bind(), name="lb", route_prefix="/lb")
    resps = [h.remote(i) for i in range(16)]
    pids = {r.result(timeout_s=30) for r in resps}
    assert len(pids) == 2, f"expected both replicas used, got {pids}"
    serve.delete("lb")


def test_user_config_reconfigure(serve_instance):
    @serve.deployment(user_config={"threshold": 1})
    class Thresholder:
        def __init__(self):
            self.threshold = None

        def reconfigure(self, config):
            self.threshold = config["threshold"]

        def __call__(self, _x):
            return self.threshold

    app = Thresholder.bind()
    h = serve.run(app, name="cfg", route_prefix="/cfg")
    assert h.remote(0).result(timeout_s=30) == 1

    # Redeploy with only user_config changed: in-place reconfigure
    Thresholder.config.user_config = {"threshold": 7}
    h = serve.run(app, name="cfg", route_prefix="/cfg")
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if h.remote(0).result(timeout_s=30) == 7:
            break
        time.sleep(0.2)
    assert h.remote(0).result(timeout_s=30) == 7
    serve.delete("cfg")


def test_http_proxy_end_to_end(serve_instance):
    @serve.deployment
    class Echo:
        def __call__(self, request: serve.Request):
            body = request.json()
            return {"path": request.path, "method": request.method,
                    "doubled": body["x"] * 2}

    serve.run(Echo.bind(), name="http", route_prefix="/echo")
    port = serve.http_port()
    data = json.dumps({"x": 5}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/echo/sub?k=v", data=data,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.status == 200
        out = json.loads(resp.read())
    assert out == {"path": "/sub", "method": "POST", "doubled": 10}

    # health + routes endpoints
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/-/healthz", timeout=10) as resp:
        assert resp.read() == b"ok"
    # 404 for unknown route
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/nope", timeout=10)
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404
    serve.delete("http")


def test_serve_batching(serve_instance):
    @serve.deployment(max_ongoing_requests=16)
    class Batched:
        def __init__(self):
            self.sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.1)
        async def handle(self, items):
            self.sizes.append(len(items))
            return [i * 2 for i in items]

        async def __call__(self, x):
            return await self.handle(x)

        def max_batch_seen(self):
            return max(self.sizes) if self.sizes else 0

    h = serve.run(Batched.bind(), name="batch", route_prefix="/batch")
    resps = [h.remote(i) for i in range(16)]
    assert [r.result(timeout_s=30) for r in resps] == \
        [i * 2 for i in range(16)]
    probe = h.options(method_name="max_batch_seen")
    assert probe.remote().result(timeout_s=30) > 1
    serve.delete("batch")


def test_autoscaling_up(serve_instance):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_ongoing_requests": 1.0,
                            "upscale_delay_s": 0.2,
                            "downscale_delay_s": 60.0})
    class Slow:
        def __call__(self, _x):
            time.sleep(0.4)
            return 1

    h = serve.run(Slow.bind(), name="auto", route_prefix="/auto")

    stop = threading.Event()

    def flood():
        while not stop.is_set():
            try:
                h.remote(0).result(timeout_s=60)
            except Exception:
                return

    threads = [threading.Thread(target=flood, daemon=True)
               for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 45
        replicas = 1
        while time.monotonic() < deadline:
            st = serve.status().get("auto")
            if st:
                replicas = st["deployments"]["Slow"]["replicas"]
                if replicas >= 2:
                    break
            time.sleep(0.3)
        assert replicas >= 2, f"autoscaler never scaled up: {serve.status()}"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    serve.delete("auto")


def test_multiplexed(serve_instance):
    @serve.deployment
    class Multi:
        @serve.multiplexed(max_num_models_per_replica=2)
        async def get_model(self, model_id: str):
            return f"model:{model_id}"

        async def __call__(self, model_id):
            model = await self.get_model(model_id)
            return model

    h = serve.run(Multi.bind(), name="mux", route_prefix="/mux")
    assert h.remote("a").result(timeout_s=30) == "model:a"
    assert h.remote("b").result(timeout_s=30) == "model:b"
    assert h.remote("a").result(timeout_s=30) == "model:a"
    serve.delete("mux")


def test_llm_deployment_through_serve(serve_instance):
    """Continuous-batched LLM replica served through the full stack:
    serve.run → router → replica actor hosting the engine (the judged
    serve configuration at debug scale)."""
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg = llama.LlamaConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_dim=128, max_seq=64, remat=False, dtype=jnp.float32)

    # Replicas are async actors already; the engine thread does the
    # batching while __call__ awaits futures.
    LLMDeployment = serve.deployment(serve.LLMServer).options(
        name="llm", num_replicas=1)
    h = serve.run(LLMDeployment.bind(cfg, max_batch=2, max_len=64,
                                     seed=11, page_size=8),
                  name="llm_app", route_prefix="/llm")
    futs = [h.remote({"prompt": [3 + i, 1, 4], "max_new_tokens": 5})
            for i in range(4)]
    results = [f.result(timeout_s=120) for f in futs]
    for r in results:
        assert len(r["tokens"]) == 5
        assert r["ttft_s"] > 0
    # Engine counters surface through the serve state API (round 8):
    # replica get_metrics carries the user callable's stats() dict.
    rm = serve.replica_metrics("llm_app", deployment="llm")
    replicas = rm["llm_app"]["llm"]
    assert replicas
    stats = next(iter(replicas.values()))["user_stats"]
    assert stats["completed"] >= 4
    assert "prefix_hit_tokens" in stats
    # The prefix-summary digest (round 11, cache-aware routing) rides
    # the same path and must UPDATE once serving commits a full block:
    # the 3-token prompts above commit nothing (page_size=8)...
    digest0 = stats["kv"]["prefix_summary"]["digest"]
    assert digest0 == 0
    # ...and a 12-token prompt commits one block, moving the digest.
    h.remote({"prompt": list(range(1, 13)),
              "max_new_tokens": 3}).result(timeout_s=120)
    rm2 = serve.replica_metrics("llm_app", deployment="llm")
    stats2 = next(iter(rm2["llm_app"]["llm"].values()))["user_stats"]
    assert stats2["kv"]["prefix_summary"]["digest"] != digest0
    serve.delete("llm_app")


def test_replica_context_and_http_options(ray_shared):
    """serve.get_replica_context identifies app/deployment/replica from
    inside the replica (ray: serve.get_replica_context); HTTPOptions is
    dict-compatible with attribute access."""
    opts = serve.HTTPOptions(host="127.0.0.1", port=0)
    assert opts.host == "127.0.0.1" and opts["port"] == 0

    @serve.deployment
    class WhereAmI:
        def __call__(self, _req=None):
            ctx = serve.get_replica_context()
            return {"app": ctx.app_name, "dep": ctx.deployment,
                    "tag": ctx.replica_tag,
                    "self": ctx.servable_object is self}

    h = serve.run(WhereAmI.bind(), name="ctxapp", route_prefix="/ctx")
    out = h.remote().result(timeout_s=120)
    assert out["app"] == "ctxapp"
    assert out["dep"] == "WhereAmI"
    assert out["tag"]
    assert out["self"] is True
    with pytest.raises(RuntimeError, match="inside a"):
        serve.get_replica_context()
    serve.delete("ctxapp")
