"""Failpoint-site consistency check (ISSUE 15 satellite).

The failpoint site list has grown to ~25 names across six PRs with no
check that a site named in CLAUDE.md or armed in a test still exists in
code — a renamed site would leave chaos tests arming a no-op and docs
pointing at nothing.  This grep-based test pins both sources against
the `failpoints.fire("...")` / `fire_async("...")` literals in the
tree.
"""
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# site shape: dotted lowercase identifiers (serve.kv_export, arena.copy)
_SITE = r"[a-z_][a-z0-9_]*\.[a-z_][a-z0-9_]*"
# Arming spec shape, as tests write it: site=action[+action...]
_ARM = re.compile(rf"({_SITE})=(?:nth:|prob:|crash|error|delay:"
                  rf"|drop\b|off\b)")
# Literal fire sites in runtime/library code.
_FIRE = re.compile(rf"fire(?:_async)?\(\s*[\"']({_SITE})[\"']")
# Backticked site tokens in CLAUDE.md prose.
_DOC_TOKEN = re.compile(rf"`({_SITE})(?:=[^`]*)?`")


def _code_sites() -> set[str]:
    out = set()
    for dirpath, _dirs, files in os.walk(os.path.join(REPO, "ray_tpu")):
        if "__pycache__" in dirpath:
            continue
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fname),
                      encoding="utf-8") as f:
                out.update(_FIRE.findall(f.read()))
    return out


def _claude_md_sites() -> set[str]:
    """Every site CLAUDE.md names as a failpoint: from each
    "[Ff]ailpoint site(s)" mention, collect backticked dotted tokens
    until the sentence ends — ';' or '.'-plus-whitespace, the doc's
    conventions separating the site list from trailing span/invariant
    prose — or a 400-char window closes."""
    with open(os.path.join(REPO, "CLAUDE.md"), encoding="utf-8") as f:
        text = f.read()
    out = set()
    for m in re.finditer(r"[Ff]ailpoint sites?", text):
        window = text[m.end():m.end() + 400]
        window = re.split(r";|\.\s", window, maxsplit=1)[0]
        out.update(_DOC_TOKEN.findall(window))
    return out


def _test_armed_sites() -> set[str]:
    """Sites armed by spec string anywhere in the test suite — except
    test_failpoints.py itself, whose synthetic names (a.b, test.probe)
    exercise the arming machinery, not real sites."""
    out = set()
    here = os.path.dirname(os.path.abspath(__file__))
    for fname in os.listdir(here):
        if not fname.endswith(".py") or fname == "test_failpoints.py":
            continue
        with open(os.path.join(here, fname), encoding="utf-8") as f:
            out.update(_ARM.findall(f.read()))
    return out


def test_scan_is_not_vacuous():
    """The greps find real data — a path/convention change must fail
    loudly, not silently allow-list nothing."""
    assert len(_code_sites()) >= 20
    assert len(_claude_md_sites()) >= 10
    assert len(_test_armed_sites()) >= 8


def test_every_claude_md_site_exists_in_code():
    missing = _claude_md_sites() - _code_sites()
    assert not missing, (
        "CLAUDE.md names failpoint sites that no "
        "failpoints.fire()/fire_async() literal implements: "
        f"{sorted(missing)}")


def test_every_test_armed_site_exists_in_code():
    missing = _test_armed_sites() - _code_sites()
    assert not missing, (
        "tests arm failpoint sites that no "
        "failpoints.fire()/fire_async() literal implements: "
        f"{sorted(missing)}")


@pytest.mark.parametrize("site", ["telemetry.harvest",
                                  "memory.harvest"])
def test_harvest_degradation_sites_present(site):
    """The observability harvest verbs keep their agent-side
    degrade-to-partial failpoint windows."""
    assert site in _code_sites()
