"""Serve response streaming: replica generator items reach the consumer
(handle and HTTP chunked) while the generator is still producing
(reference: serve ASGI StreamingResponse + DeploymentResponseGenerator,
ray: python/ray/serve/handle.py stream=True).
"""
import socket
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def app():
    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})

    @serve.deployment(max_ongoing_requests=4)
    class Streamer:
        def __call__(self, request):
            # Proxy path: request is a serve Request; handle path: dict.
            n = 4
            for i in range(n):
                yield f"tok{i} "
                time.sleep(0.3)

        def nums(self, upto):
            for i in range(upto):
                yield i * i

    handle = serve.run(Streamer.bind(), name="streamer",
                       route_prefix="/stream")
    yield handle
    serve.shutdown()


def test_handle_streaming(app):
    items = []
    t_first = None
    t0 = time.perf_counter()
    for item in app.options(method_name="nums", stream=True).remote(5):
        if t_first is None:
            t_first = time.perf_counter() - t0
        items.append(item)
    assert items == [0, 1, 4, 9, 16]


def test_handle_streaming_first_item_early(app):
    t0 = time.perf_counter()
    gen = app.options(stream=True).remote({})
    first = next(iter(gen))
    first_s = time.perf_counter() - t0
    assert first == "tok0 "
    # The generator takes ~1.2s total; the first item must not wait for it.
    assert first_s < 1.0, f"first item took {first_s:.2f}s"
    rest = list(gen)
    assert rest == ["tok1 ", "tok2 ", "tok3 "]


def test_http_chunked_streaming(app):
    port = serve.http_port()
    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    s.sendall(b"GET /stream HTTP/1.1\r\n"
              b"Host: x\r\nx-serve-stream: 1\r\n"
              b"Connection: close\r\n\r\n")
    t0 = time.perf_counter()
    buf = b""
    first_chunk_at = None
    while True:
        data = s.recv(4096)
        if not data:
            break
        buf += data
        if first_chunk_at is None and b"tok0" in buf:
            first_chunk_at = time.perf_counter() - t0
    s.close()
    head, _, body = buf.partition(b"\r\n\r\n")
    assert b"200 OK" in head
    assert b"chunked" in head.lower()
    for i in range(4):
        assert f"tok{i}".encode() in body
    assert first_chunk_at is not None and first_chunk_at < 1.2, \
        f"first chunk at {first_chunk_at}"
