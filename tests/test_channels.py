"""Mutable shm channels (accelerated-DAG edges): in-place rewrite,
exactly-once reads, writer backpressure, cross-process via actors
(reference: experimental_mutable_object_manager.h semantics).
"""
import threading
import time

import pytest

from ray_tpu.experimental import Channel


def test_write_read_repeated_in_place():
    ch = Channel.create("t_basic", max_size=4096)
    rd = Channel.open("t_basic")
    try:
        for i in range(50):
            ch.write({"step": i, "data": list(range(10))})
            out = rd.read(timeout=5)
            assert out["step"] == i
    finally:
        rd.close()
        ch.close()


def test_writer_blocks_until_reader_acks():
    ch = Channel.create("t_bp", max_size=1024)
    rd = Channel.open("t_bp")
    try:
        ch.write("a")
        with pytest.raises(TimeoutError):
            ch.write("b", timeout=0.3)    # reader never consumed "a"
        assert rd.read(timeout=1) == "a"
        ch.write("b", timeout=1)          # now it proceeds
        assert rd.read(timeout=1) == "b"
    finally:
        rd.close()
        ch.close()


def test_oversized_payload_rejected():
    ch = Channel.create("t_big", max_size=128)
    try:
        from ray_tpu.experimental.channel import ChannelFull

        with pytest.raises(ChannelFull):
            ch.write(b"x" * 4096)
    finally:
        ch.close()


def test_two_readers_each_see_every_value():
    ch = Channel.create("t_two", max_size=1024, n_readers=2)
    r1 = Channel.open("t_two")
    r2 = Channel.open("t_two")
    seen1, seen2 = [], []

    def consume(rd, out):
        for _ in range(5):
            out.append(rd.read(timeout=5))

    t1 = threading.Thread(target=consume, args=(r1, seen1))
    t2 = threading.Thread(target=consume, args=(r2, seen2))
    t1.start()
    t2.start()
    try:
        for i in range(5):
            ch.write(i, timeout=5)
        t1.join(10)
        t2.join(10)
        assert seen1 == seen2 == [0, 1, 2, 3, 4]
    finally:
        r1.close()
        r2.close()
        ch.close()


def test_channel_across_actor_processes():
    """The DAG-edge scenario: producer and consumer actors exchange
    values through the channel BY NAME — no object store traffic per
    item."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init(resources={"CPU": 4})

    @ray_tpu.remote
    class Producer:
        def __init__(self, name):
            self.ch = Channel.open(name)

        def produce(self, n):
            for i in range(n):
                self.ch.write({"i": i, "sq": i * i}, timeout=30)
            return n

    @ray_tpu.remote
    class Consumer:
        def __init__(self, name):
            self.ch = Channel.open(name)

        def consume(self, n):
            return [self.ch.read(timeout=30)["sq"] for _ in range(n)]

    ch = Channel.create("t_actors", max_size=4096)
    try:
        prod = Producer.remote("t_actors")
        cons = Consumer.remote("t_actors")
        # Warm both actors first: under full-suite load actor workers can
        # take tens of seconds to fork, which must not eat into the
        # channel-handshake timeouts below.
        ray_tpu.get([prod.produce.remote(0),
                     cons.consume.remote(0)], timeout=180)
        got_ref = cons.consume.remote(8)
        sent_ref = prod.produce.remote(8)
        assert ray_tpu.get(sent_ref, timeout=120) == 8
        assert ray_tpu.get(got_ref, timeout=120) == [i * i for i in range(8)]
    finally:
        for a in (prod, cons):
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                pass
        ch.close()


def test_throughput_beats_put_get_for_repeated_edges():
    """The point of channels: repeated small handoffs are much cheaper
    than per-item put/get through the object store."""
    ch = Channel.create("t_perf", max_size=4096)
    rd = Channel.open("t_perf")
    try:
        n = 2000
        t0 = time.perf_counter()
        for i in range(n):
            ch.write(i)
            rd.read(timeout=5)
        per_item_us = (time.perf_counter() - t0) / n * 1e6
        # Same-process round trip should be tens of µs, far below the
        # ~100µs+ of a put+get pair.
        assert per_item_us < 500, f"{per_item_us:.0f}µs per handoff"
    finally:
        rd.close()
        ch.close()


def test_extra_reader_rejected():
    """The reader set is fixed at create(): a reader beyond n_readers
    fails loudly instead of silently corrupting the ack protocol."""
    from ray_tpu.experimental.channel import ChannelError

    ch = Channel.create("t_fixed", max_size=256, n_readers=1)
    r1 = Channel.open("t_fixed")
    r2 = Channel.open("t_fixed")
    try:
        ch.write("x")
        assert r1.read(timeout=2) == "x"
        with pytest.raises(ChannelError, match="slots claimed"):
            r2.read(timeout=2)
    finally:
        r1.close()
        r2.close()
        ch.close()


def test_stale_segment_superseded_on_create():
    """A crashed owner's leftover segment must not break re-creation."""
    a = Channel.create("t_stale", max_size=256)
    a._created = False          # simulate crash: no unlink on close
    a.close()
    b = Channel.create("t_stale", max_size=256)   # supersedes
    rd = Channel.open("t_stale")
    try:
        b.write(7)
        assert rd.read(timeout=2) == 7
    finally:
        rd.close()
        b.close()


def test_closed_channel_raises_channel_closed():
    from ray_tpu.experimental.channel import ChannelClosed

    ch = Channel.create("t_closed", max_size=256)
    ch.close()
    with pytest.raises(ChannelClosed):
        ch.write("x")
    with pytest.raises(ChannelClosed):
        ch.read(timeout=0.1)
