"""ray_tpu-on-Spark launcher (ray: python/ray/util/spark/cluster_init.py).

The Spark surface is the injectable SparkJobRunner; these tests drive the
real orchestration — head startup, per-executor node-agent babysitting,
readiness wait, cancellation teardown — through LocalProcessJobRunner
(the image has no pyspark, matching the reference's local-mode tests).

Runs its own cluster (not ray_shared): the launcher owns head processes.
"""
import ray_tpu


def test_spark_cluster_lifecycle():
    from ray_tpu.utils.spark import (LocalProcessJobRunner,
                                     setup_ray_tpu_cluster,
                                     shutdown_ray_tpu_cluster)

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()

    runner = LocalProcessJobRunner()
    address, cluster = setup_ray_tpu_cluster(
        max_worker_nodes=2, num_cpus_worker_node=1, num_cpus_head_node=0,
        job_runner=runner, timeout=120.0)
    try:
        rt = cluster.connect()
        # Worker CPUs only: the head node contributes none.
        assert rt.cluster_resources().get("CPU", 0) == 2

        @rt.remote(num_cpus=1)
        def where():
            import ray_tpu

            return ray_tpu.get_runtime_context().node_id

        nodes = set(rt.get([where.remote() for _ in range(6)], timeout=120))
        # All tasks land on the two Spark "executor" nodes.
        assert 1 <= len(nodes) <= 2
    finally:
        cluster.shutdown()

    # Teardown cancelled the executor job: babysitter threads exited and
    # their node agents were terminated.
    for t in runner._threads:
        t.join(timeout=15)
        assert not t.is_alive()

    # Idempotent + global-registry path.
    shutdown_ray_tpu_cluster()
    assert not ray_tpu.is_initialized()


def test_spark_double_setup_rejected():
    from ray_tpu.utils import spark as spark_mod
    from ray_tpu.utils.spark import (LocalProcessJobRunner,
                                     RayTpuClusterOnSpark,
                                     setup_ray_tpu_cluster)

    sentinel = RayTpuClusterOnSpark("addr", [], LocalProcessJobRunner(),
                                    None, 0)
    spark_mod._active_cluster = sentinel
    try:
        try:
            setup_ray_tpu_cluster(max_worker_nodes=1,
                                  job_runner=LocalProcessJobRunner())
            raise AssertionError("second setup should be rejected")
        except RuntimeError as e:
            assert "already active" in str(e)
    finally:
        spark_mod._active_cluster = None
