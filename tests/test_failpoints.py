"""Deterministic chaos: every hard window of the runtime, by name.

The random-kill suites (test_chaos*.py) prove the availability story
statistically; THIS suite steps through each named failpoint site
(_private/failpoints.py), arms it with a deterministic action, observes
the injected fault fire (site hit counters), and asserts full recovery —
the windows that random kills only hit by luck:

  arena.alloc/copy/seal + put.publish   crash inside the put pipeline
  rpc.reply_dispatch                    reply dropped after state mutated
  rpc.io_send / rpc.io_recv             messages lost/delayed in transit
  agent.heartbeat                       liveness signal suppressed
  agent.lease_grant                     grant window errors
  agent.reserve_bundles                 agent dies mid-PG-reserve-wave
  controller.reserve_wave               controller-side wave aborts
  store.serve_chunk / store.pull_chunk  chunked transfer boundaries
  worker.lineage_resubmit               reconstruction entry point
  serve.replica_call                    replica dies mid-request
  train.step / train.group_restart      train worker dies mid-step

Every cluster-level test ends with zero dead-process arena pins
(_arena_pins_settle).  Each test runs its own cluster (it kills pieces
of it).
"""
import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu._private import failpoints
from ray_tpu.cluster_utils import Cluster

from test_chaos_adversarial import _arena_pins_settle

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_failpoints():
    """No armed site may leak between tests (or into other suites)."""
    failpoints.reset()
    yield
    failpoints.reset()


@pytest.fixture
def fp_ray():
    """Single-node runtime with a short actor-reply watchdog (the
    dropped-reply tests wait on it) and everything else stock."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    ray_tpu.init(resources={"CPU": 4},
                 _system_config={"actor_reply_resend_s": 2.0})
    yield ray_tpu
    ray_tpu.shutdown()


def _core():
    from ray_tpu._private.worker import global_worker

    return global_worker()


# --------------------------------------------------------------- module
class TestFailpointModule:
    """Pure-unit semantics of the failpoint table itself."""

    def test_parse_and_env_sync(self):
        failpoints.configure("a.b=nth:3+drop,c.d=delay:5")
        assert failpoints.ACTIVE
        assert os.environ[failpoints.ENV_VAR] == failpoints.spec()
        failpoints.reset()
        assert not failpoints.ACTIVE
        assert failpoints.ENV_VAR not in os.environ

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError):
            failpoints.configure("no_equals_sign")
        with pytest.raises(ValueError):
            failpoints.configure("a.b=frobnicate")

    def test_nth_fires_once_then_disarms(self):
        failpoints.configure("s=nth:2+drop")
        assert failpoints.fire("s") is False          # hit 1
        assert failpoints.fire("s") is True           # hit 2: fires
        assert "s" not in failpoints.spec()           # one-shot disarm
        assert failpoints.counters()["s"]["fired"] == 1

    def test_error_action_resolves_class(self):
        failpoints.configure("s=error:ValueError")
        with pytest.raises(ValueError, match="injected by failpoint"):
            failpoints.fire("s")
        failpoints.configure("s=error")
        with pytest.raises(failpoints.FailpointError):
            failpoints.fire("s")

    def test_prob_is_seed_deterministic(self):
        failpoints.configure("s=prob:0.5+drop", seed=123)
        run1 = [failpoints.fire("s") for _ in range(32)]
        failpoints.configure("s=prob:0.5+drop", seed=123)
        run2 = [failpoints.fire("s") for _ in range(32)]
        assert run1 == run2
        assert any(run1) and not all(run1)
        failpoints.configure("s=prob:0.5+drop", seed=124)
        assert [failpoints.fire("s") for _ in range(32)] != run1

    def test_delay_action(self):
        failpoints.configure("s=delay:30")
        t0 = time.monotonic()
        assert failpoints.fire("s") is False
        assert time.monotonic() - t0 >= 0.025

    def test_child_sigkill_scrubs_one_shot_crash_sites(self):
        """A SIGKILLed child while a one-shot crash site is armed in the
        SPAWNER must disarm it there too (the dying process can only
        scrub its own env) — otherwise every replacement worker inherits
        the armed spec and "fire exactly once" becomes a crash loop.
        Recurring crash sites stay armed: crashing every process is
        their contract."""
        failpoints.configure("a.b=nth:1+crash,c.d=crash,e.f=nth:2+drop")
        failpoints.on_child_sigkill()
        spec = failpoints.spec()
        assert "a.b" not in spec                       # one-shot crash: gone
        assert "c.d=crash" in spec                     # recurring: stays
        assert "e.f=nth:2+drop" in spec                # non-crash: stays
        assert "a.b" not in os.environ[failpoints.ENV_VAR]
        assert "a.b" in failpoints.counters()          # visible post-scrub

    def test_control_ops(self):
        out = failpoints.control({"op": "set", "spec": "x.y=off"})
        assert out["armed"] == "x.y=off" and out["pid"] == os.getpid()
        failpoints.fire("x.y")
        out = failpoints.control({"op": "counters"})
        assert out["counters"]["x.y"]["hits"] == 1
        assert out["counters"]["x.y"]["fired"] == 0   # "off" never fires
        out = failpoints.control({"op": "clear"})
        assert out["armed"] == ""


# ----------------------------------------------------- rpc transport
def test_io_send_windows(fp_ray):
    """rpc.io_send: delay leaves calls correct (just slower); drop makes
    the process mute until disarmed — and it recovers the moment the
    site clears."""
    core = _core()
    failpoints.configure("rpc.io_send=delay:10")
    reply, _ = core.call(core.agent_addr, "ping", {}, timeout=30.0)
    assert reply["node_id"]
    assert failpoints.counters()["rpc.io_send"]["hits"] > 0
    failpoints.configure("rpc.io_send=drop")
    with pytest.raises(Exception):
        core.call(core.agent_addr, "ping", {}, timeout=1.5)
    failpoints.reset()
    reply, _ = core.call(core.agent_addr, "ping", {}, timeout=30.0)
    assert reply["node_id"]


def test_io_recv_drop_window(fp_ray):
    """rpc.io_recv=drop: every inbound message (including our call's
    reply) is lost; the call times out instead of wedging, and clearing
    the site restores the transport."""
    core = _core()
    failpoints.configure("rpc.io_recv=drop")
    with pytest.raises(Exception):
        core.call(core.agent_addr, "ping", {}, timeout=1.5)
    counters = failpoints.counters()
    failpoints.reset()
    assert counters["rpc.io_recv"]["fired"] >= 1
    reply, _ = core.call(core.agent_addr, "ping", {}, timeout=30.0)
    assert reply["node_id"]
    # An injected ERROR on the IO thread has no caller to surface to: it
    # must degrade to drop-with-log, never kill the IO thread (which
    # would wedge every socket of the process — including the clear).
    failpoints.configure("rpc.io_recv=error")
    with pytest.raises(Exception):
        core.call(core.agent_addr, "ping", {}, timeout=1.5)
    failpoints.reset()
    reply, _ = core.call(core.agent_addr, "ping", {}, timeout=30.0)
    assert reply["node_id"]


# ------------------------------------------------- dropped actor reply
def _counter_actor():
    class Counter:
        def __init__(self):
            self.n = 0

        def arm(self, spec):
            from ray_tpu._private import failpoints as fp

            fp.configure(spec)
            return True

        def counters(self):
            from ray_tpu._private import failpoints as fp

            return fp.counters()

        def incr(self):
            self.n += 1
            return self.n

    return Counter


def test_reply_dropped_loop_path(fp_ray):
    """rpc.reply_dispatch=drop on the actor's worker: the actor MUTATED
    state, the reply vanished.  The caller's watchdog resends the same
    seqno after actor_reply_resend_s; the receiver serves the CACHED
    reply — the call completes and the state advanced exactly once."""
    Counter = _counter_actor()
    # max_task_retries forces the loop path (the fused direct path is
    # covered by the next test).
    c = ray_tpu.remote(Counter).options(max_task_retries=1).remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    # Reply hits on this worker: 1 = the arm call's own reply, 2 = the
    # next incr — which is the one that gets dropped.
    assert ray_tpu.get(c.arm.remote("rpc.reply_dispatch=nth:2+drop"),
                       timeout=30)
    t0 = time.monotonic()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
    dt = time.monotonic() - t0
    assert dt >= 1.5, f"reply can't have been dropped (completed in {dt:.2f}s)"
    # Safe retry: no double-apply.
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 3
    ctr = ray_tpu.get(c.counters.remote(), timeout=30)
    assert ctr["rpc.reply_dispatch"]["fired"] == 1
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats


def test_reply_dropped_direct_path(fp_ray):
    """Same window on the fused sync fast path (sole-in-flight,
    max_task_retries=0): the loop-side watchdog resends the SAME msgid
    and the original future resolves."""
    Counter = _counter_actor()
    c = ray_tpu.remote(Counter).remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.arm.remote("rpc.reply_dispatch=nth:2+drop"),
                       timeout=30)
    t0 = time.monotonic()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 2
    assert time.monotonic() - t0 >= 1.5
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 3
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats


def test_reply_dropped_big_reply_never_reexecutes(fp_ray):
    """Replies >64KiB shed their payload from the receiver's dedupe
    cache at completion; the watchdog's resend must hit the tombstone
    and get an explicit "reply evicted" error — NOT a silent second
    execution (the method mutated state; at-most-once is the contract
    the resend watchdog advertises)."""
    class BigCounter:
        def __init__(self):
            self.n = 0

        def arm(self, spec):
            from ray_tpu._private import failpoints as fp

            fp.configure(spec)
            return True

        def incr_big(self):
            self.n += 1
            return bytes(100_000)       # > the 64KiB reply-cache trim

        def get_n(self):
            return self.n

    c = ray_tpu.remote(BigCounter).remote()
    assert ray_tpu.get(c.get_n.remote(), timeout=60) == 0
    # Hit 1 = arm's own reply; hit 2 = incr_big's (the dropped one).
    assert ray_tpu.get(c.arm.remote("rpc.reply_dispatch=nth:2+drop"),
                       timeout=30)
    with pytest.raises(Exception, match="evicted"):
        ray_tpu.get(c.incr_big.remote(), timeout=60)
    # The execution happened EXACTLY once — the resend did not re-run it.
    assert ray_tpu.get(c.get_n.remote(), timeout=60) == 1
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats


# ------------------------------------------------- arena put pipeline
@pytest.mark.parametrize("site", ["arena.alloc", "arena.copy",
                                  "arena.seal", "put.publish"])
def test_arena_put_crash_windows(fp_ray, site):
    """Crash at each stage of the put pipeline inside an actor: the
    worker dies IN the window, the retried call completes on the
    restarted incarnation, and the crash sweep reclaims the dead
    process's half-created blocks and pins (EOWNERDEAD recovery — the
    index-publish-last invariant makes everything else rebuildable)."""
    class Putter:
        def arm(self, spec):
            from ray_tpu._private import failpoints as fp

            fp.configure(spec)
            return True

        def put_big(self):
            import numpy as np

            ref = ray_tpu.put(np.full(300_000, 7, np.uint8))
            return [ref]

        def ping(self):
            return "ok"

    p = ray_tpu.remote(Putter).options(max_restarts=2,
                                       max_task_retries=2).remote()
    assert ray_tpu.get(p.ping.remote(), timeout=60) == "ok"
    assert ray_tpu.get(p.arm.remote(f"{site}=crash"), timeout=30)
    # The crash fires mid-put; max_task_retries re-runs put_big on the
    # restarted (unarmed) incarnation, so the call COMPLETES.
    wrapped = ray_tpu.get(p.put_big.remote(), timeout=120)
    value = ray_tpu.get(wrapped[0], timeout=60)
    assert value[0] == 7 and value.nbytes == 300_000
    assert ray_tpu.get(p.ping.remote(), timeout=60) == "ok"
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), f"leaked pins: {stats}"


def test_arena_copy_error_takes_abort_path(fp_ray):
    """arena.copy=error in the DRIVER: the abort handler must free the
    creating-state block (no crash-sweep needed) and the put must still
    succeed through the RPC fallback path."""
    import numpy as np

    failpoints.configure("arena.copy=error:RuntimeError")
    ref = ray_tpu.put(np.full(300_000, 9, np.uint8))
    failpoints.reset()
    assert ray_tpu.get(ref, timeout=60)[0] == 9
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats


def test_arena_alloc_error_aborts_allocation():
    """arena.alloc=error in a LIVE process: the abort handler must free
    the just-allocated creating-state block — a live process's creating
    block is invisible to the dead-pid sweep, so anything short of an
    immediate abort leaks it until the arena fills."""
    from ray_tpu._private.native_store import Arena

    a = Arena(f"/raytpu_fpalloc_{os.getpid()}",
              capacity=8 * 1024 * 1024, create=True)
    try:
        baseline = a.stats()
        failpoints.configure("arena.alloc=error:RuntimeError")
        for i in range(3):
            with pytest.raises(RuntimeError):
                a.put_frames(f"{i:016d}".encode(), [b"x" * 300_000])
        assert failpoints.counters()["arena.alloc"]["fired"] == 3
        failpoints.reset()
        after = a.stats()
        # Nothing may survive the aborts: neither bytes nor entries.
        assert after["used"] == baseline["used"], after
        assert after["num_objects"] == baseline["num_objects"], after
        # The arena still works once disarmed.
        oid = b"Z" * 16
        assert a.put_frames(oid, [b"y" * 1000])
        assert bytes(a.get_frames(oid)[0]) == b"y" * 1000
    finally:
        failpoints.reset()
        a.close()


# ------------------------------------------------------- control verb
def test_control_verb_reaches_running_processes(fp_ray):
    """Cluster-wide broadcast through the controller arms agents AND
    already-running workers; spawn-time env inheritance covers workers
    created afterwards; clear undoes both."""
    core = _core()

    @ray_tpu.remote
    def read_spec():
        from ray_tpu._private import failpoints as fp

        return fp.spec()

    # Make sure at least one worker exists and is registered.
    assert ray_tpu.get(read_spec.remote(), timeout=60) == ""
    reply, _ = core.call(core.controller_addr, "failpoints",
                         {"op": "set", "spec": "test.probe=off",
                          "broadcast": True}, timeout=30.0)
    assert reply["armed"] == "test.probe=off"
    assert reply["nodes"], "broadcast reached no agents"
    agent_reply = next(iter(reply["nodes"].values()))
    assert agent_reply["armed"] == "test.probe=off"
    assert agent_reply.get("workers"), "agent broadcast reached no workers"
    # Any worker — already running (verb) or spawned later (env
    # inheritance from the armed agent) — sees the site.
    assert ray_tpu.get(read_spec.remote(), timeout=60) == "test.probe=off"
    reply, _ = core.call(core.controller_addr, "failpoints",
                         {"op": "clear", "broadcast": True}, timeout=30.0)
    assert reply["armed"] == ""
    assert ray_tpu.get(read_spec.remote(), timeout=60) == ""


# --------------------------------------------------------- node agent
def test_heartbeat_drop_node_dies_and_rejoins():
    """The two-level liveness contract, window by window: (1) dropped
    heartbeats alone must NOT kill a reachable node — the controller's
    direct probe saves it; (2) dropping the agent's replies too (probe
    unanswerable) must declare it dead; (3) clearing the sites lets the
    still-running agent re-register and come back ALIVE."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(1)
        core = _core()

        def node_state():
            for n in ray_tpu.nodes():
                if n["node_id"] == n1["node_id"]:
                    return n["state"]
            return "GONE"

        # (1) heartbeats suppressed, agent reachable: probe keeps it ALIVE.
        reply, _ = core.call(n1["agent_addr"], "failpoints",
                             {"op": "set", "spec": "agent.heartbeat=drop"},
                             timeout=10.0)
        assert reply["armed"] == "agent.heartbeat=drop"
        time.sleep(12.0)      # >2x node_death_timeout_s
        assert node_state() == "ALIVE", \
            "probe layer failed to save a reachable node"
        # (2) replies suppressed too: the probe goes unanswered → DEAD.
        # The set APPLIES server-side but its own reply is eaten by the
        # site it just armed — exactly the window under test.
        try:
            core.call(
                n1["agent_addr"], "failpoints",
                {"op": "set",
                 "spec": "agent.heartbeat=drop,rpc.reply_dispatch=drop"},
                timeout=5.0)
        except Exception:  # noqa: BLE001 - reply dropped by design
            pass
        deadline = time.monotonic() + 45
        while node_state() == "ALIVE" and time.monotonic() < deadline:
            time.sleep(0.5)
        assert node_state() != "ALIVE", "node never declared dead"
        # (3) clear over the SAME address: reset() lowers the flag before
        # the reply dispatches, so THIS reply gets through — and the
        # agent's next heartbeat re-registers the node.
        reply, _ = core.call(n1["agent_addr"], "failpoints",
                             {"op": "clear"}, timeout=10.0)
        assert reply["armed"] == ""
        deadline = time.monotonic() + 30
        while node_state() != "ALIVE" and time.monotonic() < deadline:
            time.sleep(0.5)
        assert node_state() == "ALIVE", "node never rejoined after clear"

        @ray_tpu.remote
        def ping():
            return "ok"

        assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_lease_grant_error_window(fp_ray):
    """agent.lease_grant=nth:1+error: the first grant dies AFTER the
    resource acquisition — the release path must run (no double-booked
    resources) and the submitter's pusher re-requests, so the task
    completes and the node's full capacity stays usable."""
    core = _core()
    reply, _ = core.call(core.agent_addr, "failpoints",
                         {"op": "set",
                          "spec": "agent.lease_grant=nth:1+error"},
                         timeout=10.0)
    assert reply["armed"]

    @ray_tpu.remote
    def work(x):
        return x + 1

    assert ray_tpu.get(work.remote(1), timeout=120) == 2
    reply, _ = core.call(core.agent_addr, "failpoints", {"op": "counters"},
                         timeout=10.0)
    assert reply["counters"]["agent.lease_grant"]["fired"] == 1
    # Full capacity proves the failed grant released its acquisition.
    @ray_tpu.remote(num_cpus=4)
    def wide():
        return "fits"

    assert ray_tpu.get(wide.remote(), timeout=120) == "fits"
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats


# ------------------------------------------------ PG reserve wave
def test_agent_crash_mid_reserve_wave_no_leaked_bundles():
    """agent.reserve_bundles=nth:1+crash on node 2: the agent dies
    mid-wave with bundle 1 locally reserved but never granted.  The
    controller's STRICT rollback must release node 1's reservation (the
    dead node's dies with it), and node 1's FULL capacity must remain
    placeable afterwards."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.utils.placement_group import (placement_group,
                                                   remove_placement_group)

        cluster.wait_for_nodes(2)
        core = _core()
        reply, _ = core.call(
            n2["agent_addr"], "failpoints",
            {"op": "set", "spec": "agent.reserve_bundles=nth:1+crash"},
            timeout=10.0)
        assert reply["armed"]
        # Two 2-CPU bundles can only place across BOTH nodes: the wave
        # reserves on n1, crashes n2 mid-reserve, and must roll back.
        pg = placement_group([{"CPU": 2}, {"CPU": 2}], strategy="PACK")
        assert pg.ready(timeout=10) is False, \
            "PG became ready despite the agent dying mid-wave"
        # n2 is dead; n1's 2 CPUs must NOT be leaked by the rollback: a
        # single-bundle 2-CPU group must become ready on n1.
        pg2 = placement_group([{"CPU": 2}], strategy="PACK")
        assert pg2.ready(timeout=60), "rollback leaked node 1's bundle"

        @ray_tpu.remote(num_cpus=2, placement_group=pg2)
        def inside():
            return "placed"

        assert ray_tpu.get(inside.remote(), timeout=120) == "placed"
        remove_placement_group(pg2)
        remove_placement_group(pg)
        # The dead node is eventually observed dead.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
            if states.get(n2["node_id"]) != "ALIVE":
                break
            time.sleep(0.5)
        assert states.get(n2["node_id"]) != "ALIVE"
        stats = _arena_pins_settle()
        assert not stats.get("swept_dead_pins", 0), stats
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_controller_reserve_wave_error_retries():
    """controller.reserve_wave=nth:1+error: the first wave aborts before
    any reserve RPC; the PG scheduler's retry loop places the group on
    the next pass (one-shot site), and the controller's counters prove
    the window fired."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.utils.placement_group import (placement_group,
                                                   remove_placement_group)

        cluster.wait_for_nodes(1)
        core = _core()
        reply, _ = core.call(
            core.controller_addr, "failpoints",
            {"op": "set", "spec": "controller.reserve_wave=nth:1+error"},
            timeout=10.0)
        assert reply["armed"]
        pg = placement_group([{"CPU": 1}], strategy="PACK")
        assert pg.ready(timeout=60), "PG never recovered from the aborted wave"
        reply, _ = core.call(core.controller_addr, "failpoints",
                             {"op": "counters"}, timeout=10.0)
        assert reply["counters"]["controller.reserve_wave"]["fired"] == 1
        remove_placement_group(pg)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# ------------------------------------------- chunked pull + lineage
def test_source_crash_mid_chunked_pull_lineage_recovers():
    """store.serve_chunk=nth:3+crash on the node holding a large object:
    the source agent dies after serving two chunks of the pull.  The
    getter must fall through its locations, hit the lineage-resubmit
    window (observed via the driver's own counters), re-run the
    producing task on the surviving node, and return the right bytes —
    with zero dead-process pins afterwards."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    # This test exercises the CHUNK protocol's crash window.  Since
    # round 10 same-host pulls (which is all an in-process Cluster has)
    # take the direct-shm fast path and never cross a chunk boundary —
    # kill it for every process this test spawns (and for this driver,
    # which does the pulling) so the window under test is the one that
    # runs.
    os.environ["RAY_TPU_SHM_PULL"] = "0"
    cluster = Cluster('{"transfer_chunk_bytes": 1048576}')
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 2, "remote": 1, "pin1": 1})
    n2 = cluster.add_node(resources={"CPU": 2, "remote": 1})
    ray_tpu.init(address=cluster.address,
                 _system_config={"transfer_chunk_bytes": 1048576})
    try:
        cluster.wait_for_nodes(2)
        core = _core()

        # Blocker holds n1's "remote" so the producer MUST run on n2;
        # killed afterwards so the lineage re-run fits on n1.
        @ray_tpu.remote(resources={"remote": 1, "pin1": 1}, num_cpus=0)
        class Blocker:
            def ping(self):
                return "held"

        blocker = Blocker.remote()
        assert ray_tpu.get(blocker.ping.remote(), timeout=60) == "held"

        @ray_tpu.remote(resources={"remote": 0.5}, max_retries=4)
        def big(fill):
            import numpy as np

            return np.full(6_000_000, fill, dtype=np.uint8)

        ref_warm = big.remote(2)
        ref = big.remote(3)
        done, _ = ray_tpu.wait([ref_warm, ref], num_returns=2,
                               timeout=120)
        assert len(done) == 2, "producers never finished"
        ray_tpu.kill(blocker)
        time.sleep(1.0)   # agent frees the blocker's resources

        # Phase A — healthy chunked pull with the chunk-boundary site
        # armed on the PULLING agent (n1): proves the window is crossed.
        core.call(n1["agent_addr"], "failpoints",
                  {"op": "set", "spec": "store.pull_chunk=delay:1"},
                  timeout=10.0)
        warm = ray_tpu.get(ref_warm, timeout=120)
        assert warm[0] == 2
        reply, _ = core.call(n1["agent_addr"], "failpoints",
                             {"op": "counters"}, timeout=10.0)
        assert reply["counters"]["store.pull_chunk"]["hits"] >= 1, \
            "pull never crossed a chunk boundary on the pulling agent"

        # Phase B — n2's agent dies serving a chunk of the second
        # object; the driver's get must fall through to lineage.
        reply, _ = core.call(
            n2["agent_addr"], "failpoints",
            {"op": "set", "spec": "store.serve_chunk=nth:3+crash"},
            timeout=10.0)
        assert reply["armed"]
        failpoints.configure("worker.lineage_resubmit=delay:1")

        value = ray_tpu.get(ref, timeout=180)
        assert value[0] == 3 and value.nbytes == 6_000_000
        assert failpoints.counters()[
            "worker.lineage_resubmit"]["fired"] >= 1, \
            "recovery did not go through the lineage window"
        # The crash (not a timeout) is what killed n2.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
            if states.get(n2["node_id"]) != "ALIVE":
                break
            time.sleep(0.5)
        assert states.get(n2["node_id"]) != "ALIVE"
        failpoints.reset()
        stats = _arena_pins_settle()
        assert not stats.get("swept_dead_pins", 0), stats
    finally:
        os.environ.pop("RAY_TPU_SHM_PULL", None)
        failpoints.reset()
        ray_tpu.shutdown()
        cluster.shutdown()


# ----------------------------------------------- error-message audit
def test_object_lost_error_names_locations_and_lineage():
    """The surfaced ObjectLostError carries the diagnosis (ref, every
    location tried, lineage verdict) instead of a bare 12-char id —
    round 9 also fixed the exception class truncating its message."""
    from ray_tpu.exceptions import ObjectLostError

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2, "remote": 1})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"remote": 0.5}, max_retries=0)
        def big():
            import numpy as np

            return np.ones(3_000_000, np.uint8)

        ref = big.remote()
        done, _ = ray_tpu.wait([ref], num_returns=1, timeout=120)
        assert done
        cluster.kill_node(n2)
        # Wait for death detection so the skip-dead-location path logs
        # its reason rather than burning RPC timeouts.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
            if states.get(n2["node_id"]) != "ALIVE":
                break
            time.sleep(0.5)
        with pytest.raises(ObjectLostError) as ei:
            ray_tpu.get(ref, timeout=120)
        msg = str(ei.value)
        assert ref.hex()[:12] in msg, msg
        assert "locations tried" in msg, msg
        assert "lineage" in msg, msg
        assert ei.value.object_id == ref.hex()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# -------------------------------------------------------------- serve
def test_replica_crash_mid_request_requeues(fp_ray):
    """serve.replica_call=nth:1+crash on ONE replica of a 2-replica
    deployment: the next request routed to it dies mid-request (before
    the user callable ran) and must complete on the other replica via
    the handle's dead-replica requeue — no caller ever sees the death."""
    from ray_tpu import serve

    serve.start()
    try:
        @serve.deployment(num_replicas=2, max_ongoing_requests=4)
        class Svc:
            def arm(self):
                import os as _os

                from ray_tpu._private import failpoints as fp

                fp.arm("serve.replica_call", "nth:1+crash")
                return _os.getpid()

            def ping(self, i):
                import os as _os

                return (i, _os.getpid())

        h = serve.run(Svc.bind(), name="fp_app", route_prefix="/fp")
        armed_pid = h.arm.remote().result(timeout_s=60)
        # Sequential requests: pow-2 routing sends one to the armed
        # replica almost immediately; THAT request crashes it and must
        # still succeed on the survivor.
        results = []
        for i in range(12):
            results.append(h.ping.remote(i).result(timeout_s=120))
        assert [r[0] for r in results] == list(range(12))
        # The window genuinely fired: the armed replica process is gone.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                os.kill(armed_pid, 0)
                time.sleep(0.5)
            except ProcessLookupError:
                break
        else:
            raise AssertionError(
                f"armed replica {armed_pid} still alive — the "
                f"serve.replica_call window never fired")
        stats = _arena_pins_settle()
        assert not stats.get("swept_dead_pins", 0), stats
    finally:
        serve.shutdown()


# -------------------------------------------------------------- train
def _fp_train_loop(config):
    """Checkpoint-per-step loop; rank 0 arms train.step=crash ONCE at
    the configured step (marker file bounds it to one incarnation) — the
    crash then fires INSIDE session.report, i.e. mid-step."""
    import os as _os
    import time as _time

    from ray_tpu import train
    from ray_tpu._private import failpoints as fp
    from ray_tpu.train import Checkpoint

    ctx = train.get_context()
    ckpt = train.get_checkpoint()
    start = ckpt.to_dict()["step"] + 1 if ckpt else 0
    for i in range(start, config["total_steps"]):
        marker = config["kill_marker"]
        if (i == config["kill_at"] and ctx.get_world_rank() == 0
                and not _os.path.exists(marker)):
            open(marker, "w").close()
            fp.arm("train.step", "crash")
        train.report({"step": i, "start": start,
                      "rank": ctx.get_world_rank()},
                     checkpoint=Checkpoint.from_dict({"step": i}))
        _time.sleep(config.get("step_sleep_s", 0.4))


def test_train_step_crash_group_restart(fp_ray, tmp_path):
    """train.step=crash mid-run: the group restart (train.group_restart
    window instrumented with a delay in the driver) resumes from the
    NEWEST checkpoint, not the run's origin."""
    from ray_tpu.train import (FailureConfig, JaxTrainer, RunConfig,
                               ScalingConfig)

    failpoints.configure("train.group_restart=delay:10")
    marker = tmp_path / "killed_once"
    trainer = JaxTrainer(
        _fp_train_loop,
        train_loop_config={"total_steps": 6, "kill_at": 3,
                           "step_sleep_s": 0.4,
                           "kill_marker": str(marker)},
        scaling_config=ScalingConfig(num_workers=2,
                                     num_cpus_per_worker=0.5),
        run_config=RunConfig(name="fp_train", storage_path=str(tmp_path),
                             failure_config=FailureConfig(max_failures=1)))
    result = trainer.fit()
    assert marker.exists(), "the train.step window never armed"
    assert result.error is None, result.error
    assert result.metrics["step"] == 5
    # Resumed from the newest checkpoint: some incarnation started > 0.
    starts = {m.get("start") for m in result.metrics_history}
    assert any(s > 0 for s in starts if s is not None), starts
    # The group-restart window fired in THIS process.
    assert failpoints.counters()["train.group_restart"]["fired"] >= 1
    failpoints.reset()
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), stats
