"""Object spilling: arena-full puts spill LRU objects to disk and restore
on demand.

Mirrors ray: python/ray/tests/test_object_spilling.py (fill the store past
capacity, then read everything back).
"""
import numpy as np
import pytest


def test_spill_and_restore_roundtrip():
    """Direct StoreRunner-level roundtrip with a tiny arena."""
    from ray_tpu._private.config import Config
    from ray_tpu._private.object_store import StoreRunner

    cfg = Config()
    cfg.object_store_memory = 4 * 1024 * 1024        # 4 MB arena
    runner = StoreRunner("ab" * 8, cfg)
    try:
        payloads = {}
        for i in range(8):                            # 8 x 1 MB > arena
            oid = bytes([i]) * 16
            data = np.full(1024 * 1024, i, np.uint8).tobytes()
            payloads[oid] = data
            assert runner.put_with_spill(oid, [data])
        assert runner.spilled, "nothing was spilled"
        import asyncio

        async def fetch(oid):
            reply, blobs = await runner.rpc_store_get(
                {"object_id": oid.hex()}, [])
            assert reply["found"], oid
            return bytes(blobs[0])

        for oid, data in payloads.items():
            assert asyncio.run(fetch(oid)) == data
    finally:
        runner.close()


def test_spill_through_public_api():
    """End to end: puts past store capacity keep working and get() sees
    every object after spilling."""
    import ray_tpu

    ray_tpu.init(resources={"CPU": 2},
                 object_store_memory=8 * 1024 * 1024)
    try:
        refs, arrays = [], []
        for i in range(10):                           # 10 x 1.5MB > 8MB
            a = np.full(1_500_000, i, np.uint8)
            arrays.append(a)
            refs.append(ray_tpu.put(a))
        for a, r in zip(arrays, refs):
            np.testing.assert_array_equal(ray_tpu.get(r), a)
    finally:
        ray_tpu.shutdown()
