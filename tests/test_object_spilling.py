"""Object spilling: arena-full puts spill LRU objects to disk and restore
on demand.

Mirrors ray: python/ray/tests/test_object_spilling.py (fill the store past
capacity, then read everything back).
"""
import numpy as np
import pytest


def test_spill_and_restore_roundtrip():
    """Direct StoreRunner-level roundtrip with a tiny arena."""
    from ray_tpu._private.config import Config
    from ray_tpu._private.object_store import StoreRunner

    import asyncio

    cfg = Config()
    cfg.object_store_memory = 4 * 1024 * 1024        # 4 MB arena
    runner = StoreRunner("ab" * 8, cfg)

    async def go():
        payloads = {}
        for i in range(8):                            # 8 x 1 MB > arena
            oid = bytes([i]) * 16
            data = np.full(1024 * 1024, i, np.uint8).tobytes()
            payloads[oid] = data
            assert await runner.put_with_spill(oid, [data])
        assert runner.spilled, "nothing was spilled"
        for oid, data in payloads.items():
            reply, blobs = await runner.rpc_store_get(
                {"object_id": oid.hex()}, [])
            assert reply["found"], oid
            assert bytes(blobs[0]) == data

    try:
        asyncio.run(go())
    finally:
        runner.close()


def test_spill_through_public_api():
    """End to end: puts past store capacity keep working and get() sees
    every object after spilling."""
    import ray_tpu

    ray_tpu.init(resources={"CPU": 2},
                 object_store_memory=8 * 1024 * 1024)
    try:
        refs, arrays = [], []
        for i in range(10):                           # 10 x 1.5MB > 8MB
            a = np.full(1_500_000, i, np.uint8)
            arrays.append(a)
            refs.append(ray_tpu.put(a))
        for a, r in zip(arrays, refs):
            np.testing.assert_array_equal(ray_tpu.get(r), a)
    finally:
        ray_tpu.shutdown()


def test_chunked_cross_node_pull():
    """A big object stored on node A transfers to node B in parallel
    chunks and reads back intact (ray: ObjectManager chunked push,
    64MB chunks / 8 in flight)."""
    import asyncio

    from ray_tpu._private.config import Config
    from ray_tpu._private.object_store import StoreRunner
    from ray_tpu._private.rpc import ClientPool, RpcServer

    import zmq.asyncio

    async def go():
        cfg = Config()
        cfg.object_store_memory = 64 * 1024 * 1024
        cfg.transfer_chunk_bytes = 1024 * 1024       # small for the test
        ctx = zmq.asyncio.Context.instance()
        servers, runners = [], []
        for node in ("aa" * 8, "bb" * 8):
            srv = RpcServer(ctx)
            pool = ClientPool(ctx)
            runner = StoreRunner(node, cfg)
            runner.register_handlers(srv, pool)
            srv.start()
            servers.append(srv)
            runners.append(runner)
        a, b = runners
        oid = b"\x07" * 16
        payload = np.random.default_rng(0).integers(
            0, 255, 8 * 1024 * 1024, np.uint8).tobytes()   # 8 chunks
        assert await a.put_with_spill(oid, [b"hdr", payload])
        reply = await b.rpc_store_pull(
            {"object_id": oid.hex(), "from": [servers[0].address]}, [])
        assert reply["ok"], "chunked pull failed"
        frames = b.backend.get(oid)
        assert bytes(frames[0]) == b"hdr"
        assert bytes(frames[1]) == payload
        for srv in servers:
            srv.close()
        for r in runners:
            r.close()

    asyncio.run(go())


def test_chunked_pull_from_spilled_source():
    """Chunk serving works when the source object lives in a spill file
    (identical on-disk bundle layout)."""
    import asyncio

    from ray_tpu._private.config import Config
    from ray_tpu._private.object_store import StoreRunner
    from ray_tpu._private.rpc import ClientPool, RpcServer

    import zmq.asyncio

    async def go():
        cfg = Config()
        cfg.object_store_memory = 64 * 1024 * 1024
        cfg.transfer_chunk_bytes = 1024 * 1024
        ctx = zmq.asyncio.Context.instance()
        srv_a = RpcServer(ctx)
        a = StoreRunner("cc" * 8, cfg)
        a.register_handlers(srv_a, ClientPool(ctx))
        srv_a.start()
        srv_b = RpcServer(ctx)
        b = StoreRunner("dd" * 8, cfg)
        b.register_handlers(srv_b, ClientPool(ctx))
        srv_b.start()

        oid = b"\x09" * 16
        payload = bytes(range(256)) * (3 * 1024 * 32)     # ~3MB
        assert await a.put_with_spill(oid, [payload])
        # Force it onto disk on the source.
        while a.backend.contains(oid):
            assert await a._spill_one()
        assert oid in a.spilled
        reply = await b.rpc_store_pull(
            {"object_id": oid.hex(), "from": [srv_a.address]}, [])
        assert reply["ok"]
        frames = b.backend.get(oid)
        assert bytes(frames[0]) == payload
        srv_a.close()
        srv_b.close()
        a.close()
        b.close()

    asyncio.run(go())
