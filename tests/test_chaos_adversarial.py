"""Adversarial chaos for the ownership/borrow/lineage protocol.

Mirrors the intent of ray: python/ray/_private/test_utils.py:1433-1549
(ResourceKillerActor / NodeKillerActor) and the nightly chaos suites —
the subtlest code in the repo (owner tables, borrow pins, lineage
resubmission, chunked pulls, PG state) under process kills, asserting
full recovery and no leaked arena objects.

Each test runs its own Cluster (it kills processes).
"""
import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.chaos


@pytest.fixture
def fresh_cluster():
    """One head + one 4-CPU node; torn down per test (kills happen)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head()
    n1 = cluster.add_node(resources={"CPU": 4})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(1)
    yield cluster, n1
    ray_tpu.shutdown()
    cluster.shutdown()


def _arena_pins_settle(timeout: float = 15.0) -> dict:
    """Post-chaos sweep check: the arena must converge to zero
    dead-process pins and zero pin-table overflow (the no-leaked-objects
    assertion; the sweep itself is the reaper's 5s-cadence job)."""
    from ray_tpu._private.worker import global_worker

    core = global_worker()
    deadline = time.monotonic() + timeout
    last = {}
    while time.monotonic() < deadline:
        reply, _ = core.call(core.agent_addr, "store_stats",
                             {"sweep": True}, timeout=10.0)
        last = reply
        if not reply.get("swept_dead_pins", 0) \
                and not reply.get("pin_overflow", 0):
            return reply
        time.sleep(1.0)
    return last


def _make_actor_classes():
    """Local class definitions: cloudpickle ships them BY VALUE, so the
    attach-mode cluster's workers need no importable test module."""

    class Holder:
        """Actor that OWNS objects (puts them itself), hands out refs."""

        def __init__(self):
            self.refs = []

        def make(self, nbytes: int):
            import numpy as np

            ref = ray_tpu.put(np.ones(nbytes, np.uint8))
            self.refs.append(ref)
            return [ref]      # list wrapper: ref travels as a VALUE

        def pid(self):
            import os

            return os.getpid()

    class Borrower:
        def __init__(self):
            self.held = []

        def borrow(self, wrapped):
            self.held.append(wrapped[0])
            return True

        def read(self, i):
            import numpy as np

            return int(np.sum(ray_tpu.get(self.held[i])[:4]))

    return Holder, Borrower


def test_owner_dies_while_borrowed(fresh_cluster):
    """Kill an object's OWNER while a borrower holds the ref: borrower
    reads must fail with a clean error (not hang), the cluster stays
    healthy, and the arena sweeps the dead owner's pins."""
    import os
    import signal

    Holder, Borrower = _make_actor_classes()
    holder = ray_tpu.remote(Holder).options(max_restarts=0).remote()
    borrower = ray_tpu.remote(Borrower).remote()
    wrapped = ray_tpu.get(holder.make.remote(300_000))
    assert ray_tpu.get(borrower.borrow.remote(wrapped))
    # Borrower can read while the owner lives.
    assert ray_tpu.get(borrower.read.remote(0)) == 4
    owner_pid = ray_tpu.get(holder.pid.remote())
    os.kill(owner_pid, signal.SIGKILL)
    time.sleep(1.0)
    # The borrower that ALREADY resolved the object may keep serving its
    # cached immutable copy (sealed objects never mutate, so this beats
    # the reference's owner-death semantics on availability) — but it
    # must never HANG.
    try:
        assert ray_tpu.get(borrower.read.remote(0), timeout=30) == 4
    except Exception:  # noqa: BLE001 - clean failure is also acceptable
        pass
    # A FRESH borrower has no cache: resolving through the dead owner
    # must surface a clean error (put objects have no lineage), not hang.
    _, Borrower2 = _make_actor_classes()
    fresh = ray_tpu.remote(Borrower2).remote()
    ray_tpu.get(fresh.borrow.remote(wrapped), timeout=30)
    with pytest.raises(Exception):
        ray_tpu.get(fresh.read.remote(0), timeout=30)
    # Cluster still schedules fresh work.
    @ray_tpu.remote
    def ping():
        return "ok"

    assert ray_tpu.get(ping.remote(), timeout=60) == "ok"
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), f"leaked pins: {stats}"


def test_owner_kills_under_borrow_load(fresh_cluster):
    """Churn: many owners create objects, borrowers read them, owners
    die mid-stream.  Every read either succeeds or raises cleanly; the
    driver never deadlocks; no arena leaks afterwards."""
    import os
    import signal

    Holder, Borrower = _make_actor_classes()
    holders = [ray_tpu.remote(Holder).options(max_restarts=0).remote()
               for _ in range(3)]
    borrower = ray_tpu.remote(Borrower).remote()
    n_reads = 0
    for round_i in range(3):
        for h in holders:
            try:
                wrapped = ray_tpu.get(h.make.remote(100_000), timeout=30)
                ray_tpu.get(borrower.borrow.remote(wrapped), timeout=30)
                n_reads += 1
            except Exception:  # noqa: BLE001 - holder already killed
                pass
        if round_i == 1:
            pid = ray_tpu.get(holders[0].pid.remote())
            os.kill(pid, signal.SIGKILL)
    ok, failed = 0, 0
    for i in range(n_reads):
        try:
            ray_tpu.get(borrower.read.remote(i), timeout=30)
            ok += 1
        except Exception:  # noqa: BLE001
            failed += 1
    assert ok >= 1, "no borrow reads survived"
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), f"leaked pins: {stats}"


def test_agent_killed_mid_chunked_pull():
    """Kill the remote node's agent while the driver pulls a chunked
    object from it: the get must recover via lineage (the producing task
    reruns on a surviving node) — ray: object reconstruction under node
    failure."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster('{"transfer_chunk_bytes": 1048576}')
    cluster.start_head()
    cluster.add_node(resources={"CPU": 2})
    n2 = cluster.add_node(resources={"CPU": 2, "remote": 1})
    ray_tpu.init(address=cluster.address,
                 _system_config={"transfer_chunk_bytes": 1048576})
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(resources={"remote": 0.01}, max_retries=4)
        def big_far():
            import numpy as np

            return np.arange(6_000_000, dtype=np.uint8)

        # Warm-up proves the topology works at all.
        probe = ray_tpu.get(big_far.remote(), timeout=120)
        assert probe[5] == 5

        ref = big_far.remote()
        killer = threading.Timer(0.4, cluster.kill_node, args=(n2,))
        killer.start()
        try:
            # After the kill the lease/pull fails; lineage resubmits.
            # The task needs "remote" which died with n2 — so it must
            # surface an infeasible/lost error OR complete if the pull
            # won the race.  Either way: no hang.
            ray_tpu.get(ref, timeout=90)
        except Exception:  # noqa: BLE001 - acceptable: resource gone
            pass
        finally:
            killer.cancel()

        # A CPU-only variant must fully recover via lineage on node 1.
        @ray_tpu.remote(max_retries=4)
        def big_anywhere(x):
            import numpy as np

            return np.full(3_000_000, x, dtype=np.uint8)

        out = ray_tpu.get([big_anywhere.remote(7), big_anywhere.remote(9)],
                          timeout=120)
        assert out[0][0] == 7 and out[1][-1] == 9
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_controller_killed_during_pg_churn(tmp_path):
    """Hard-kill + restart the controller WHILE placement groups churn:
    churn continues after the restart and a fresh PG still schedules
    (ray: test_gcs_fault_tolerance.py PG paths)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    cluster = Cluster()
    cluster.start_head(snapshot_path=str(tmp_path / "snap.json"))
    cluster.add_node(resources={"CPU": 4})
    ray_tpu.init(address=cluster.address)
    try:
        from ray_tpu.utils.placement_group import (placement_group,
                                                   remove_placement_group)

        cluster.wait_for_nodes(1)
        stop = threading.Event()
        outcomes = {"created": 0, "errors": 0}

        def churn():
            while not stop.is_set():
                try:
                    pg = placement_group([{"CPU": 0.5}], strategy="PACK")
                    pg.ready(timeout=20)
                    outcomes["created"] += 1
                    remove_placement_group(pg)
                except Exception:  # noqa: BLE001 - mid-restart windows
                    outcomes["errors"] += 1
                    time.sleep(0.3)

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        time.sleep(1.5)
        cluster.kill_head()
        time.sleep(0.5)
        cluster.restart_head()
        time.sleep(4.0)
        stop.set()
        t.join(timeout=30)
        created_after_restart = outcomes["created"]
        # Fresh PG end-to-end after the restart.
        pg = placement_group([{"CPU": 1}], strategy="PACK")
        pg.ready(timeout=60)

        @ray_tpu.remote(num_cpus=0.5, placement_group=pg)
        def inside():
            return "placed"

        assert ray_tpu.get(inside.remote(), timeout=60) == "placed"
        remove_placement_group(pg)
        assert created_after_restart >= 1, \
            f"PG churn never succeeded: {outcomes}"
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_actor_restart_storm_with_state(fresh_cluster):
    """Kill restartable actors repeatedly under call load: every call
    eventually lands on a fresh incarnation (max_task_retries), and no
    arena pins leak from the dead incarnations."""
    import os
    import signal

    @ray_tpu.remote(max_restarts=10, max_task_retries=10)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    counters = [Counter.remote() for _ in range(2)]
    for c in counters:
        assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    for kill_round in range(2):
        pid = ray_tpu.get(counters[0].pid.remote(), timeout=60)
        os.kill(pid, signal.SIGKILL)
        # Calls during/after the kill retry onto the restarted actor.
        vals = ray_tpu.get([counters[0].incr.remote() for _ in range(5)],
                           timeout=120)
        assert len(vals) == 5
        # Restart resets state: counts restart from 1 each incarnation.
        assert vals[-1] >= 1
    stats = _arena_pins_settle()
    assert not stats.get("swept_dead_pins", 0), f"leaked pins: {stats}"


def test_dead_submitter_leases_reaped(fresh_cluster):
    """A driver that dies holding worker leases must have them reaped by
    the agent's submitter-liveness probe (ray: the raylet returns leased
    workers when the owner's connection drops) — otherwise its CPUs leak
    and later placements hang PENDING forever (the round-3 client-proxy
    suite wedge)."""
    import subprocess
    import sys
    import textwrap

    from ray_tpu._private.worker import global_worker

    controller = global_worker().controller_addr
    # A throwaway driver attaches, creates a NAMED actor (holds 1 CPU)
    # and leaves tasks in flight, then is SIGKILLed.
    script = textwrap.dedent(f"""
        import sys, time
        sys.path.insert(0, "/root/repo")
        import ray_tpu
        ray_tpu.init(address="{controller}")

        @ray_tpu.remote
        def slow():
            import time as t
            t.sleep(60)
            return 1

        @ray_tpu.remote
        class Pinned:
            def ping(self):
                return 1

        a = Pinned.options(name="leaker", lifetime="detached").remote()
        ray_tpu.get(a.ping.remote())
        refs = [slow.remote() for _ in range(3)]   # leases held
        print("READY", flush=True)
        time.sleep(300)
    """)
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if b"READY" in line:
            break
    else:
        raise TimeoutError("leaker driver never became ready")
    proc.kill()
    proc.wait(timeout=10)
    # The reaper probes submitters every ~5s, 3 strikes: within ~45s the
    # leases return and a full-width placement fits again (the detached
    # actor legitimately keeps its 1 CPU).
    deadline = time.monotonic() + 90

    @ray_tpu.remote(num_cpus=3)
    def wide():
        return "fits"

    assert ray_tpu.get(wide.remote(), timeout=90) == "fits"
